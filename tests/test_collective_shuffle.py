"""Whole-stage collective shuffle (DESIGN.md §22): schedule selection,
compiled-vs-per-block byte identity, fetch+merge fusion, mid-stage
degrade, and lane-balanced reduce cuts — all on the emulated
``JAX_PLATFORMS=cpu`` topology tier-1 runs on."""

import numpy as np
import pytest

from sparkrdma_tpu.locations import (
    BlockLocation,
    PartitionLocation,
    ShuffleManagerId,
)
from sparkrdma_tpu.obs import get_registry
from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle, HashPartitioner
from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
from sparkrdma_tpu.utils.config import TpuShuffleConf

BLOCK = 64 << 10  # above the 16 KiB deviceFetch.minBlockBytes default


def _loc(pid, length, exec_id, mkey=1, handle=1, coords=0):
    return PartitionLocation(
        ShuffleManagerId("host", 1234, exec_id),
        pid,
        BlockLocation(
            0, length, mkey, device_coords=coords, arena_handle=handle
        ),
    )


def _counter(name, role):
    return get_registry().counter(name, role=role)


@pytest.fixture()
def cluster():
    from sparkrdma_tpu.shuffle.device_io import DeviceShuffleIO

    conf = TpuShuffleConf({"tpu.shuffle.transport": "python"})
    driver = TpuShuffleManager(conf, is_driver=True)
    ex_map = TpuShuffleManager(conf, is_driver=False, executor_id="cs-map")
    ex_red = TpuShuffleManager(conf, is_driver=False, executor_id="cs-red")
    driver.register_shuffle(
        BaseShuffleHandle(
            shuffle_id=91, num_maps=1, partitioner=HashPartitioner(3)
        )
    )
    io_map, io_red = DeviceShuffleIO(ex_map), DeviceShuffleIO(ex_red)
    try:
        yield conf, io_map, io_red
    finally:
        io_red.stop()
        io_map.stop()
        ex_red.stop()
        ex_map.stop()
        driver.stop()


def _publish_shards(io_map, shards=3, seed=57):
    """``shards`` map windows, 3 partitions each -> 3 blocks per pid,
    all from one publisher (one DMA lane)."""
    rng = np.random.default_rng(seed)
    windows, all_data = [], {}
    for _ in range(shards):
        data = {p: rng.integers(0, 256, BLOCK + p, np.uint8) for p in range(3)}
        windows.append(io_map.stage_device_blocks(91, data))
        for p, arr in data.items():
            all_data.setdefault(p, []).append(arr)
    io_map.publish_staged_batch(91, windows, num_map_outputs_each=1)
    return all_data


# ----------------------------------------------------------------------
# schedule compilation (plan-level, synthetic location sets)
# ----------------------------------------------------------------------
def test_schedule_selection_and_passthrough(cluster):
    """auto resolves ring for <=2 source lanes and a2a above; explicit
    knob wins; sub-minBlocks stages and disabled compilers pass every
    location through untouched."""
    from sparkrdma_tpu.shuffle import device_fetch as df
    from sparkrdma_tpu.shuffle.collective import ShuffleScheduleCompiler

    conf, io_map, io_red = cluster
    for i in range(3):
        df.register_arena(f"cs-lane-{i}", io_map.device_buffers)
    try:
        comp = ShuffleScheduleCompiler(
            conf, io_red.device_buffers, "cs-sched"
        )
        three_lanes = [
            _loc(p, BLOCK, f"cs-lane-{p}", mkey=10 + p) for p in range(3)
        ]
        plan = comp.plan(three_lanes)
        assert plan.schedule == "a2a"
        assert plan.waves and not plan.passthrough
        assert plan.device_blocks == 3

        two_lanes = [
            _loc(p, BLOCK, f"cs-lane-{p % 2}", mkey=20 + p) for p in range(3)
        ]
        assert comp.plan(two_lanes).schedule == "ring"

        conf.set("tpu.shuffle.collective.schedule", "ring")
        try:
            assert comp.plan(three_lanes).schedule == "ring"
        finally:
            conf.set("tpu.shuffle.collective.schedule", "auto")

        # below minBlocks: the per-block planner keeps the whole stage
        solo = comp.plan([_loc(0, BLOCK, "cs-lane-0")])
        assert not solo.waves and len(solo.passthrough) == 1

        # a location with no device extension never schedules
        mixed = three_lanes + [_loc(9, BLOCK, "cs-lane-0", handle=0)]
        plan = comp.plan(mixed)
        assert len(plan.passthrough) == 1
        assert plan.passthrough[0].partition_id == 9

        conf.set("tpu.shuffle.collective.enabled", "false")
        try:
            off = comp.plan(three_lanes)
            assert not off.waves and len(off.passthrough) == 3
        finally:
            conf.set("tpu.shuffle.collective.enabled", "true")
    finally:
        for i in range(3):
            df.unregister_arena(f"cs-lane-{i}", io_map.device_buffers)


def test_wave_formation_buckets_and_pid_grouping(cluster):
    """Waves cut at partition boundaries under waveBytes, with both
    axes power-of-two bucketed so ragged stages share program shapes."""
    from sparkrdma_tpu.ops.exchange import round_bucket, round_rows
    from sparkrdma_tpu.shuffle import device_fetch as df
    from sparkrdma_tpu.shuffle.collective import ShuffleScheduleCompiler

    conf, io_map, io_red = cluster
    df.register_arena("cs-lane-w", io_map.device_buffers)
    try:
        comp = ShuffleScheduleCompiler(conf, io_red.device_buffers, "cs-wf")
        # ragged lengths across 3 pids, 2 blocks each
        locs = [
            _loc(p, BLOCK + 1000 * k, "cs-lane-w", mkey=30 + 2 * p + k)
            for p in range(3)
            for k in range(2)
        ]
        plan = comp.plan(locs)
        assert plan.fusable_pids == frozenset({0, 1, 2})
        (wave,) = plan.waves
        assert wave.rows_b == round_rows(6)
        longest = max(loc.block.length for loc in locs)
        assert wave.bucket_elems == round_bucket(longest)
        # pid groups are contiguous in the wave (fusion precondition)
        pids = [r.loc.partition_id for r in wave.rows]
        assert pids == sorted(pids)

        # a tight wave budget splits at pid boundaries
        conf.set("tpu.shuffle.collective.waveBytes", "192k")
        try:
            plan = comp.plan(locs)
            assert len(plan.waves) > 1
            for w in plan.waves:
                assert [r.loc.partition_id for r in w.rows] == sorted(
                    r.loc.partition_id for r in w.rows
                )
        finally:
            conf.set("tpu.shuffle.collective.waveBytes", "64m")
    finally:
        df.unregister_arena("cs-lane-w", io_map.device_buffers)


# ----------------------------------------------------------------------
# execution byte identity (in-process cluster)
# ----------------------------------------------------------------------
def test_collective_vs_per_block_vs_host_byte_identity(cluster):
    """The same stage fetched three ways — compiled collective,
    per-block device pulls, host triple — lands byte-identical block
    multisets, and the collective counters prove which path ran."""
    conf, io_map, io_red = cluster
    data = _publish_shards(io_map)
    plans = _counter("collective.plans", "cs-red")
    blocks = _counter("collective.blocks", "cs-red")
    p0, b0 = plans.value, blocks.value

    def fetch_multiset():
        got = io_red.fetch_device_blocks(91, 0, 3, timeout_s=30)
        try:
            return {
                p: sorted(bytes(b.read(0, b.length)) for b in got[p])
                for p in range(3)
            }
        finally:
            for bufs in got.values():
                for b in bufs:
                    b.free()

    via_collective = fetch_multiset()
    assert plans.value - p0 == 1, "compiler did not engage"
    assert blocks.value - b0 == 9, "not every block rode a wave"

    conf.set("tpu.shuffle.collective.enabled", "false")
    via_per_block = fetch_multiset()
    assert plans.value - p0 == 1, "disabled compiler still planned"

    conf.set("tpu.shuffle.deviceFetch.enabled", "false")
    via_host = fetch_multiset()

    want = {p: sorted(a.tobytes() for a in data[p]) for p in range(3)}
    assert via_collective == want
    assert via_per_block == want
    assert via_host == want


def test_fused_merge_matches_host_triple(cluster):
    """fused=True lands ONE merged slab per fully-covered partition,
    equal to the unfused wave rows concatenated in merge order — and
    the underlying block multiset matches the host triple exactly."""
    conf, io_map, io_red = cluster
    data = _publish_shards(io_map, seed=61)
    fused_c = _counter("collective.fused_merges", "cs-red")
    f0 = fused_c.value

    unfused = io_red.fetch_device_blocks(91, 0, 3, timeout_s=30)
    try:
        # wave-row order IS the deterministic merge order
        expect = {
            p: b"".join(bytes(b.read(0, b.length)) for b in unfused[p])
            for p in range(3)
        }
        multiset = {
            p: sorted(bytes(b.read(0, b.length)) for b in unfused[p])
            for p in range(3)
        }
    finally:
        for bufs in unfused.values():
            for b in bufs:
                b.free()

    fused = io_red.fetch_device_blocks(91, 0, 3, timeout_s=30, fused=True)
    try:
        for p in range(3):
            assert len(fused[p]) == 1, "fusion must land one slab per pid"
            assert bytes(fused[p][0].read(0, fused[p][0].length)) == expect[p]
    finally:
        for bufs in fused.values():
            for b in bufs:
                b.free()
    assert fused_c.value - f0 == 3

    # the fused content is the host triple's blocks, concatenated
    conf.set("tpu.shuffle.deviceFetch.enabled", "false")
    host = io_red.fetch_device_blocks(91, 0, 3, timeout_s=30)
    try:
        for p in range(3):
            host_set = sorted(bytes(b.read(0, b.length)) for b in host[p])
            assert host_set == multiset[p]
            assert host_set == sorted(a.tobytes() for a in data[p])
    finally:
        for bufs in host.values():
            for b in bufs:
                b.free()

    # global off-switch: fused=True silently returns per-block shape
    conf.set("tpu.shuffle.deviceFetch.enabled", "true")
    conf.set("tpu.shuffle.collective.fusedMerge", "false")
    try:
        got = io_red.fetch_device_blocks(91, 0, 3, timeout_s=30, fused=True)
        try:
            assert all(len(got[p]) == 3 for p in range(3))
        finally:
            for bufs in got.values():
                for b in bufs:
                    b.free()
    finally:
        conf.set("tpu.shuffle.collective.fusedMerge", "true")


def test_eviction_mid_stage_degrades_silently(cluster):
    """A slab evicted between plan and pin degrades its row to the
    host triple — zero errors, byte-identical output, degrade counted,
    and (under fusion) only ITS partition unfuses."""
    conf, io_map, io_red = cluster
    data = _publish_shards(io_map, seed=67)
    degrades = _counter("collective.degrades", "cs-red")
    d0 = degrades.value

    # evict ONE of partition 1's three slabs (window 0 stages pids
    # 0,1,2 in order, so flat index 1 is w0/p1)
    victim = io_map._arena_published[91][1]
    victim.spill_to_host()
    assert victim.spilled

    got = io_red.fetch_device_blocks(91, 0, 3, timeout_s=30, fused=True)
    try:
        assert len(got[0]) == 1 and len(got[2]) == 1, "other pids stay fused"
        assert len(got[1]) == 3, "degraded pid must unfuse"
        # fused pids carry all their blocks (order is the merge order;
        # membership + total length pin the content)
        for p in (0, 2):
            blob = bytes(got[p][0].read(0, got[p][0].length))
            assert len(blob) == sum(len(a) for a in data[p])
            for a in data[p]:
                assert a.tobytes() in blob
        have1 = sorted(bytes(b.read(0, b.length)) for b in got[1])
        assert have1 == sorted(a.tobytes() for a in data[1])
    finally:
        for bufs in got.values():
            for b in bufs:
                b.free()
    assert degrades.value - d0 == 1, "exactly the evicted row degrades"


def test_whole_stage_eviction_falls_back_to_host(cluster):
    """Every scheduled slab evicted: the stage still completes byte-
    exact through the host triple with one degrade per block."""
    conf, io_map, io_red = cluster
    data = _publish_shards(io_map, seed=71, shards=1)
    degrades = _counter("collective.degrades", "cs-red")
    d0 = degrades.value
    for abuf in io_map._arena_published[91]:
        abuf.spill_to_host()
    got = io_red.fetch_device_blocks(91, 0, 3, timeout_s=30)
    try:
        for p in range(3):
            assert bytes(got[p][0].read(0, len(data[p][0]))) == (
                data[p][0].tobytes()
            )
    finally:
        for bufs in got.values():
            for b in bufs:
                b.free()
    assert degrades.value - d0 == 3


def test_split_phase_collective_pull(cluster):
    """fetch_host_blocks routes wave rows back as DevicePulledBlock
    entries (always unfused — the pipeline's seams are per block) that
    flow through verify/stage untouched."""
    from sparkrdma_tpu.shuffle.device_fetch import DevicePulledBlock

    conf, io_map, io_red = cluster
    data = _publish_shards(io_map, seed=73, shards=1)
    blocks = _counter("collective.blocks", "cs-red")
    b0 = blocks.value
    got = io_red.fetch_host_blocks(91, 0, 3, timeout_s=30)
    staged = {}
    for p, hbs in got.items():
        out = []
        for hb in hbs:
            assert isinstance(hb, DevicePulledBlock)
            out.append(io_red.stage_host_block(io_red.verify_host_block(hb)))
        staged[p] = out
    assert blocks.value - b0 == 3
    try:
        for p in range(3):
            assert bytes(staged[p][0].read(0, len(data[p][0]))) == (
                data[p][0].tobytes()
            )
    finally:
        for bufs in staged.values():
            for b in bufs:
                b.free()


# ----------------------------------------------------------------------
# double-buffered pipeline (DESIGN.md §22 pipelining)
# ----------------------------------------------------------------------
def test_pipeline_depth_byte_identity_and_overlap(cluster, monkeypatch):
    """depth>1 changes the overlap, never the bytes: the same multi-
    wave stage fetched at depth 1 and depth 2 lands identical block
    multisets, the overlap counter stays zero at depth 1 (nothing was
    in flight during any issue/consume) and goes positive at depth 2."""
    from sparkrdma_tpu.obs import attr

    conf, io_map, io_red = cluster
    # a stale breakdown from an earlier test could veto the tuner;
    # irrelevant here but keep the stage's wave count deterministic
    monkeypatch.setattr(attr, "_last_breakdown", None)
    conf.set("tpu.shuffle.collective.autoTune", "false")
    conf.set("tpu.shuffle.collective.waveBytes", "192k")
    data = _publish_shards(io_map, seed=79)
    overlap = _counter("collective.wave_overlap_ms", "cs-red")
    waves = get_registry().counter(
        "collective.waves", role="cs-red", schedule="ring"
    )

    def fetch_multiset():
        got = io_red.fetch_device_blocks(91, 0, 3, timeout_s=30)
        try:
            return {
                p: sorted(bytes(b.read(0, b.length)) for b in got[p])
                for p in range(3)
            }
        finally:
            for bufs in got.values():
                for b in bufs:
                    b.free()

    conf.set("tpu.shuffle.collective.pipelineDepth", "1")
    o0, w0 = overlap.value, waves.value
    depth1 = fetch_multiset()
    assert waves.value - w0 > 1, "stage must cut into multiple waves"
    assert overlap.value == o0, "depth 1 must never overlap"

    conf.set("tpu.shuffle.collective.pipelineDepth", "2")
    o1 = overlap.value
    depth2 = fetch_multiset()
    assert overlap.value > o1, "depth 2 must overlap issue with consume"

    want = {p: sorted(a.tobytes() for a in data[p]) for p in range(3)}
    assert depth1 == want
    assert depth2 == want


def test_pipeline_drain_on_midstage_abort(cluster, monkeypatch):
    """A wave that dies mid-pipeline (its landing wait fails while the
    next wave's transfers are already airborne) degrades ITS rows to
    the host triple without unwinding the stage: output byte-identical,
    every pin released, no slab leaked on either endpoint."""
    from sparkrdma_tpu.obs import attr
    from sparkrdma_tpu.ops import remote_copy

    conf, io_map, io_red = cluster
    monkeypatch.setattr(attr, "_last_breakdown", None)
    conf.set("tpu.shuffle.collective.autoTune", "false")
    conf.set("tpu.shuffle.collective.waveBytes", "192k")
    conf.set("tpu.shuffle.collective.pipelineDepth", "2")
    base_red = io_red.device_buffers.in_use_bytes
    data = _publish_shards(io_map, seed=83)
    degrades = _counter("collective.degrades", "cs-red")
    d0 = degrades.value

    real_wait = remote_copy.emulated_wave_wait
    calls = {"n": 0}

    def flaky_wait(inflight):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("injected: wave landing failed in flight")
        return real_wait(inflight)

    monkeypatch.setattr(remote_copy, "emulated_wave_wait", flaky_wait)
    got = io_red.fetch_device_blocks(91, 0, 3, timeout_s=30)
    try:
        have = {
            p: sorted(bytes(b.read(0, b.length)) for b in got[p])
            for p in range(3)
        }
        assert have == {
            p: sorted(a.tobytes() for a in data[p]) for p in range(3)
        }
    finally:
        for bufs in got.values():
            for b in bufs:
                b.free()
    assert calls["n"] > 1, "injection must hit mid-pipeline, not last wave"
    assert degrades.value - d0 > 0, "the dead wave's rows must degrade"
    # leak checks: no pin outlives the stage on the source arena, and
    # every local slab went back to the pool with the frees above
    assert not io_map.device_buffers._pins
    assert io_red.device_buffers.in_use_bytes == base_red


def test_autotuner_converges_on_second_stage(cluster, monkeypatch):
    """The first identical stage runs monolithic (one wave under the
    default 64m budget) and is observed; the SECOND runs with the
    tuner's re-cut budget (multiple waves for the pipeline to overlap)
    and converges — no further adjustment on the third run, and no
    slowdown from the re-cut."""
    import time as _time

    from sparkrdma_tpu.obs import attr

    conf, io_map, io_red = cluster
    # the gate must judge THIS run, not a breakdown some earlier test
    # published; None means no veto
    monkeypatch.setattr(attr, "_last_breakdown", None)
    data = _publish_shards(io_map, seed=89)
    adjusts = _counter("collective.autotune_adjustments", "cs-red")
    waves = get_registry().counter(
        "collective.waves", role="cs-red", schedule="ring"
    )
    a0 = adjusts.value

    def timed_fetch():
        t0 = _time.perf_counter()
        w0 = waves.value
        got = io_red.fetch_device_blocks(91, 0, 3, timeout_s=30)
        wall = _time.perf_counter() - t0
        try:
            have = {
                p: sorted(bytes(b.read(0, b.length)) for b in got[p])
                for p in range(3)
            }
        finally:
            for bufs in got.values():
                for b in bufs:
                    b.free()
        return have, wall, waves.value - w0

    first, wall1, waves1 = timed_fetch()
    assert waves1 == 1, "default budget must run the stage monolithic"
    assert adjusts.value - a0 == 1, "first observation must re-cut"

    second, wall2, waves2 = timed_fetch()
    assert waves2 > 1, "second identical stage must run the tuned cut"
    assert adjusts.value - a0 == 1, "same stats -> same choice: converged"

    third, wall3, waves3 = timed_fetch()
    assert waves3 == waves2
    assert adjusts.value - a0 == 1

    want = {p: sorted(a.tobytes() for a in data[p]) for p in range(3)}
    assert first == second == third == want
    # not-slower gate, honest about the rig: sub-resolution walls say
    # nothing about a regression either way (the structural asserts
    # above are the convergence proof regardless)
    if wall1 < 0.02:
        pytest.skip(
            f"stage wall {wall1 * 1e3:.1f}ms below timing resolution on "
            "this rig; cannot resolve the not-slower comparison"
        )
    assert min(wall2, wall3) <= wall1 * 2.5 + 0.05, (
        "tuned stage must not be slower than the untuned first run"
    )


def test_autotuner_converges_structurally(cluster, monkeypatch):
    """Timing-free half of the convergence proof (the not-slower test
    above may skip on rigs whose stage wall is below resolution): the
    second identical stage plans with the tuned budget and the choice
    is stable across runs."""
    from sparkrdma_tpu.obs import attr

    conf, io_map, io_red = cluster
    monkeypatch.setattr(attr, "_last_breakdown", None)
    _publish_shards(io_map, seed=97)
    adjusts = _counter("collective.autotune_adjustments", "cs-red")
    waves = get_registry().counter(
        "collective.waves", role="cs-red", schedule="ring"
    )
    a0 = adjusts.value
    per_run = []
    for _ in range(3):
        w0 = waves.value
        got = io_red.fetch_device_blocks(91, 0, 3, timeout_s=30)
        for bufs in got.values():
            for b in bufs:
                b.free()
        per_run.append(waves.value - w0)
    assert per_run[0] == 1
    assert per_run[1] > 1 and per_run[2] == per_run[1]
    assert adjusts.value - a0 == 1


# ----------------------------------------------------------------------
# lane-balanced reduce cuts (planner-level)
# ----------------------------------------------------------------------
def test_planner_lane_balanced_cuts():
    """Equal byte totals hide a one-lane hotspot; the lane-aware cost
    (num_lanes * hottest lane) re-cuts the ranges around it while the
    totals-only plan stays static."""
    from sparkrdma_tpu.shuffle.planner import AdaptivePartitioner

    conf = TpuShuffleConf()
    p, n = 8, 4
    sizes = [100] * p
    lane_sizes = {src: [25] * p for src in ("la", "lb", "lc", "ld")}
    for src in ("lb", "lc", "ld"):
        lane_sizes[src][5] = 0
    lane_sizes["la"][5] = 100  # same total, one lane carries it all

    lane_plans = get_registry().counter("collective.lane_plans", role="driver")
    c0 = lane_plans.value
    ap = AdaptivePartitioner(conf)
    base = ap.plan(sizes, n)
    assert base == [(0, 2), (2, 4), (4, 6), (6, 8)], "uniform stays static"
    laned = ap.plan(sizes, n, lane_sizes=lane_sizes)
    assert lane_plans.value - c0 == 1
    assert laned != base, "lane hotspot must move the cuts"
    # structural safety: contiguous cover of [0, p), at most n ranges
    assert len(laned) <= n
    assert laned[0][0] == 0 and laned[-1][1] == p
    for (a, b), (c, d) in zip(laned, laned[1:]):
        assert b == c

    # balanced lanes change nothing
    even = {src: [25] * p for src in ("la", "lb", "lc", "ld")}
    assert ap.plan(sizes, n, lane_sizes=even) == base
