"""Multi-process cluster: driver + executor subprocesses over real TCP.

The reference's topology — one endpoint per process, data moving
executor-to-executor with the driver as metadata hub only — exercised
with genuine OS processes and cloudpickled closures."""

import collections

import pytest

from sparkrdma_tpu.engine.cluster import ClusterContext
from sparkrdma_tpu.utils.config import TpuShuffleConf


def test_multiprocess_wordcount():
    words = ["tpu", "shuffle", "rdma", "mesh", "ici", "dcn"]

    def make_map(seed):
        def fn():
            for i in range(600):
                yield (words[(seed * 7 + i) % len(words)], 1)

        return fn

    def reduce_counts(it):
        acc = collections.Counter()
        for k, v in it:
            acc[k] += v
        return dict(acc)

    with ClusterContext(num_executors=2) as cc:
        parts = cc.run_map_reduce(
            [make_map(s) for s in range(4)], num_partitions=4,
            reduce_fn=reduce_counts,
        )
    merged = collections.Counter()
    for p in parts:
        merged.update(p)
    assert sum(merged.values()) == 4 * 600
    assert set(merged) == set(words)
    expected = collections.Counter()
    for s in range(4):
        for i in range(600):
            expected[words[(s * 7 + i) % len(words)]] += 1
    assert merged == expected


def test_multiprocess_native_transport():
    """Executor processes shuffling over the C++ data plane."""
    from sparkrdma_tpu.native.transport_lib import available

    if not available():
        pytest.skip("native transport unavailable")
    conf = TpuShuffleConf({"tpu.shuffle.transport": "native"})

    def gen():
        return iter([(i % 5, i) for i in range(1000)])

    def collect(it):
        return sorted(it)

    with ClusterContext(num_executors=2, conf=conf) as cc:
        parts = cc.run_map_reduce([gen, gen], num_partitions=2, reduce_fn=collect)
    rows = [kv for p in parts for kv in p]
    assert len(rows) == 2000
    by_key = collections.Counter(k for k, _ in rows)
    assert all(by_key[k] == 400 for k in range(5))


def test_map_failure_surfaces_to_driver():
    def bad():
        raise RuntimeError("boom in a worker process")

    with ClusterContext(num_executors=2) as cc:
        with pytest.raises(RuntimeError, match="boom"):
            cc.run_map_reduce([bad], num_partitions=1)
