"""Distributed hash join vs a dict-based reference."""

import numpy as np

from sparkrdma_tpu.models.hashjoin import HashJoin
from sparkrdma_tpu.parallel.mesh import make_mesh


def _tables(n_build=300, n_probe=2000, seed=0):
    rng = np.random.default_rng(seed)
    build_keys = rng.choice(1 << 20, size=n_build, replace=False).astype(np.uint32)
    build_vals = rng.integers(0, 1 << 20, n_build).astype(np.int32)
    # ~70% of probes hit, 30% miss
    hit = rng.random(n_probe) < 0.7
    probe_keys = np.where(
        hit,
        rng.choice(build_keys, size=n_probe),
        rng.integers(1 << 20, 1 << 21, n_probe),
    ).astype(np.uint32)
    probe_vals = np.arange(n_probe, dtype=np.int32)
    return build_keys, build_vals, probe_keys, probe_vals


def test_join_matches_dict_reference():
    bk, bv, pk, pv = _tables()
    hj = HashJoin(make_mesh())
    out = hj.join(bk, bv, pk, pv)
    assert len(out) == len(pk)  # one output row per probe row
    lookup = dict(zip(bk.tolist(), bv.tolist()))
    for k, p, j in out:
        want = lookup.get(k, -1)
        assert j == want, (k, p, j, want)
    # every probe row accounted for exactly once
    assert sorted(out[:, 1].tolist()) == list(range(len(pk)))


def test_join_all_misses():
    bk = np.array([1, 2, 3], dtype=np.uint32)
    bv = np.array([10, 20, 30], dtype=np.int32)
    pk = np.array([100, 200], dtype=np.uint32)
    pv = np.array([0, 1], dtype=np.int32)
    out = HashJoin(make_mesh()).join(bk, bv, pk, pv)
    assert (out[:, 2] == -1).all()


def test_join_skewed_keys_overflow_retry():
    # all keys in one radix range forces the capacity-doubling retry
    bk = np.arange(100, dtype=np.uint32)  # all in partition 0
    bv = bk.astype(np.int32)
    pk = np.zeros(500, dtype=np.uint32)
    pv = np.arange(500, dtype=np.int32)
    out = HashJoin(make_mesh(), capacity_factor=1.1).join(bk, bv, pk, pv)
    assert len(out) == 500
    assert (out[:, 2] == 0).all()  # every probe hit build key 0 -> val 0
