"""Device ALS (iterative wide shuffle) vs numpy reference."""

import math

import numpy as np

from sparkrdma_tpu.models.als import ALS, reference_als, rmse
from sparkrdma_tpu.parallel.mesh import make_mesh


def _ratings(n_users, n_items, m, seed=0):
    """Low-rank ground truth + noise, so ALS has signal to recover."""
    rng = np.random.default_rng(seed)
    true_u = rng.normal(size=(n_users, 4))
    true_v = rng.normal(size=(n_items, 4))
    users = rng.integers(0, n_users, m)
    items = rng.integers(0, n_items, m)
    vals = (true_u[users] * true_v[items]).sum(1) + 0.01 * rng.normal(size=m)
    return np.stack([users, items, vals], axis=1).astype(np.float64)


def _padded_init(als, n_users, n_items, seed=0):
    e = als.num_shards
    nu = int(math.ceil(n_users / e))
    ni = int(math.ceil(n_items / e))
    rng = np.random.default_rng(seed)
    u0 = (rng.normal(size=(e * nu, als.rank)) * 0.1).astype(np.float32)
    v0 = (rng.normal(size=(e * ni, als.rank)) * 0.1).astype(np.float32)
    return u0[:n_users], v0[:n_items]


def test_als_single_iteration_matches_reference():
    n_u, n_i = 48, 40
    ratings = _ratings(n_u, n_i, 600)
    als = ALS(make_mesh(), rank=4, reg=0.1)
    u, v = als.fit(ratings, n_u, n_i, iters=1, seed=0)
    u0, v0 = _padded_init(als, n_u, n_i, seed=0)
    ru, rv = reference_als(ratings, n_u, n_i, rank=4, reg=0.1, iters=1,
                           u0=u0, v0=v0)
    np.testing.assert_allclose(u, ru, rtol=2e-3, atol=2e-4)
    np.testing.assert_allclose(v, rv, rtol=2e-3, atol=2e-4)


def test_als_converges_and_tracks_reference_rmse():
    n_u, n_i = 64, 56
    ratings = _ratings(n_u, n_i, 1500, seed=2)
    als = ALS(make_mesh(), rank=6, reg=0.05)
    u, v = als.fit(ratings, n_u, n_i, iters=8, seed=0)
    got = rmse(u, v, ratings)
    u0, v0 = _padded_init(als, n_u, n_i, seed=0)
    ru, rv = reference_als(ratings, n_u, n_i, rank=6, reg=0.05, iters=8,
                           u0=u0, v0=v0)
    want = rmse(ru, rv, ratings)
    # recovered a rank-4 signal: fit should be far below the data scale
    assert got < 0.5
    assert abs(got - want) < 5e-3


def test_als_cold_rows_stay_finite():
    # users/items with zero ratings must solve to zeros, not NaNs
    ratings = np.array([[0, 0, 1.0], [1, 1, 2.0]])
    als = ALS(make_mesh(), rank=3)
    u, v = als.fit(ratings, 10, 10, iters=3)
    assert np.isfinite(u).all() and np.isfinite(v).all()
    assert np.abs(u[5]).sum() == 0  # cold user
