"""Engine-level workloads: golden-result jobs comparing the shuffle path
to plain-Python computation (SURVEY.md §4 'workload-level truth')."""

import random

import pytest

from sparkrdma_tpu.engine.context import TpuContext


@pytest.fixture(scope="module")
def ctx():
    c = TpuContext(num_executors=2)
    yield c
    c.stop()


def test_wordcount(ctx):
    words = [random.Random(7).choice("the quick brown fox jumps over lazy dog".split())
             for _ in range(5000)]
    rdd = ctx.parallelize(words, 4).map(lambda w: (w, 1)).reduce_by_key(lambda a, b: a + b)
    got = dict(rdd.collect())
    expected = {}
    for w in words:
        expected[w] = expected.get(w, 0) + 1
    assert got == expected


def test_sort_by_key_total_order(ctx):
    rng = random.Random(13)
    data = [(rng.randrange(10_000), i) for i in range(8000)]
    rdd = ctx.parallelize(data, 4).sort_by_key(num_partitions=5)
    out = rdd.collect()
    keys = [k for k, _ in out]
    assert keys == sorted(keys)
    assert sorted(out) == sorted(data)


def test_group_by_key(ctx):
    data = [(i % 7, i) for i in range(700)]
    got = dict(ctx.parallelize(data, 3).group_by_key(4).collect())
    for k in range(7):
        assert sorted(got[k]) == list(range(k, 700, 7))


def test_join(ctx):
    left = [(i % 5, f"l{i}") for i in range(20)]
    right = [(i % 5, f"r{i}") for i in range(10)]
    got = sorted(ctx.parallelize(left, 2).join(ctx.parallelize(right, 2)).collect())
    expected = sorted(
        (k, (lv, rv)) for k, lv in left for k2, rv in right if k == k2
    )
    assert got == expected


def test_chained_shuffles(ctx):
    # shuffle → narrow → shuffle (multi-stage lineage)
    data = [(i % 10, 1) for i in range(1000)]
    rdd = (
        ctx.parallelize(data, 4)
        .reduce_by_key(lambda a, b: a + b)
        .map(lambda kv: (kv[1], kv[0]))
        .sort_by_key(num_partitions=3)
    )
    out = rdd.collect()
    assert [k for k, _ in out] == [100] * 10
