"""HBM slab pool tests — the device registered-memory plane.

Mirrors the buffer-pool property targets (reuse/leak accounting,
RdmaBufferManager.java:131-141; power-of-two size classing :103-118)."""

import pytest

from sparkrdma_tpu.ops.hbm_arena import (
    MIN_BLOCK_SIZE,
    DeviceBufferManager,
    _size_class,
)


def test_size_class_rounding():
    assert _size_class(1) == MIN_BLOCK_SIZE
    assert _size_class(MIN_BLOCK_SIZE) == MIN_BLOCK_SIZE
    assert _size_class(MIN_BLOCK_SIZE + 1) == MIN_BLOCK_SIZE * 2
    assert _size_class(1 << 20) == 1 << 20


def test_stage_read_roundtrip():
    mgr = DeviceBufferManager()
    data = bytes(range(256)) * 100
    buf = mgr.stage_bytes(data)
    assert buf.length == len(data)
    assert buf.capacity >= len(data)
    assert buf.read() == data
    assert buf.read(16, 16) == data[16:32]
    buf.free()
    mgr.stop()


def test_stage_view_typed_u32():
    """u32 staging: host-side reinterpret, byte-accurate readback, and
    spill/restore that survive a non-uint8 slab dtype (the merge path
    consumes keys directly — on-device byte->word assembly would pad
    the [..., 4] minor dim 4->128 under TPU tiling)."""
    import numpy as np

    mgr = DeviceBufferManager()
    keys = np.arange(7000, dtype=np.uint32)
    buf = mgr.stage_view(memoryview(keys.view(np.uint8)), keys.nbytes,
                         dtype=np.uint32)
    assert buf.length == keys.nbytes
    assert str(buf.array.dtype) == "uint32"
    assert buf.array.shape[0] == buf.capacity // 4
    assert np.array_equal(
        np.frombuffer(buf.read(0, keys.nbytes), np.uint32), keys
    )
    # unaligned byte read off a typed slab
    assert buf.read(2, 6) == keys.view(np.uint8)[2:8].tobytes()
    # spill -> restore keeps contents and dtype
    buf.spill_to_host()
    assert buf.read(0, keys.nbytes) == keys.tobytes()
    buf.ensure_device()
    assert str(buf.array.dtype) == "uint32"
    assert np.array_equal(
        np.frombuffer(buf.read(0, keys.nbytes), np.uint32), keys
    )
    buf.free()
    mgr.stop()


def test_pinned_working_set_never_victimized():
    """Restoring a held working set must not thrash: making room for
    one member may never spill another (b.array would be None under a
    direct consumer) — and while the pin is held, OTHER pool traffic
    can't victimize the set either, even a long-resident member that
    would otherwise be the global LRU. A set larger than the budget
    fails loudly."""
    budget = 4 * MIN_BLOCK_SIZE
    mgr = DeviceBufferManager(max_bytes=budget)
    bufs = [mgr.stage_bytes(bytes([i]) * 100) for i in range(8)]  # spills
    assert mgr.spill_count >= 4
    held = bufs[:4]  # exactly fits the budget
    with mgr.pinned_on_device(held):
        assert all(not b.spilled and b.array is not None for b in held)
        assert mgr.in_use_bytes <= budget
        # every OTHER buffer got pushed out, never a set member
        assert all(b.spilled for b in bufs[4:])
        # concurrent-traffic shape: with the whole budget pinned, new
        # demand has nothing to evict and must fail loudly — never
        # silently spill a pinned member
        with pytest.raises(MemoryError):
            mgr.stage_bytes(b"x" * 100)
        assert all(not b.spilled for b in held)
    # pins dropped: the same demand now evicts an (ex-)member fine
    extra = mgr.stage_bytes(b"x" * 100)
    assert sum(b.spilled for b in bufs[:4]) == 1
    extra.free()
    with pytest.raises(MemoryError):
        with mgr.pinned_on_device(bufs[:5]):  # 5 slabs > 4-slab budget
            pass
    # ensure_device_all remains as the non-holding convenience form
    mgr.ensure_device_all(held)
    assert all(not b.spilled for b in held)
    for b in bufs:
        b.free()
    mgr.stop()


def test_three_tier_spill_hbm_host_disk(tmp_path):
    """SURVEY §7.3(4): HBM -> host RAM -> disk, byte-exact reads from
    every tier, transparent climb back, accounting that returns to
    zero, and no spill files left behind."""
    import os

    budget = 2 * MIN_BLOCK_SIZE       # 2 slabs in HBM
    host_cap = 2 * MIN_BLOCK_SIZE     # 2 slabs in host RAM
    mgr = DeviceBufferManager(
        max_bytes=budget, max_host_bytes=host_cap, spill_dir=str(tmp_path)
    )
    payload = [bytes([i]) * (MIN_BLOCK_SIZE - 64) for i in range(6)]
    bufs = [mgr.stage_bytes(p) for p in payload]
    # 6 slabs through a 2-slab HBM budget: 4 spilled to host, and the
    # 2-slab host cap cascaded 2 of those onward to disk
    assert mgr.spill_count >= 4
    assert mgr.disk_spill_count >= 2
    assert mgr.in_use_bytes <= budget
    assert mgr.host_bytes <= host_cap
    tiers = {"device": 0, "host": 0, "disk": 0}
    for b in bufs:
        tiers["disk" if b.on_disk else "host" if b._host is not None
              else "device"] += 1
    assert tiers == {"device": 2, "host": 2, "disk": 2}
    # byte-exact from every tier (disk reads via memmap, no restore)
    for b, p in zip(bufs, payload):
        assert b.read(0, len(p)) == p
    # climb a disk-tier buffer all the way back to the device
    deep = next(b for b in bufs if b.on_disk)
    deep.ensure_device()
    assert deep.array is not None and not deep.spilled
    assert deep.read(0, deep.length) == payload[bufs.index(deep)]
    assert mgr.in_use_bytes <= budget and mgr.host_bytes <= host_cap
    for b in bufs:
        b.free()
    assert mgr.in_use_bytes == 0 and mgr.host_bytes == 0
    assert list(tmp_path.iterdir()) == [], "spill files leaked"
    mgr.stop()


def test_prefetch_restores_in_background(tmp_path):
    """prefetch() climbs a spilled set back to HBM off-thread; a later
    pinned_on_device is then a fast no-op."""
    budget = 2 * MIN_BLOCK_SIZE
    mgr = DeviceBufferManager(
        max_bytes=budget, max_host_bytes=MIN_BLOCK_SIZE,
        spill_dir=str(tmp_path),
    )
    payload = [bytes([i]) * 200 for i in range(4)]
    bufs = [mgr.stage_bytes(p) for p in payload]
    assert any(b.spilled for b in bufs[:2])  # pushed out by later stages
    done = mgr.prefetch(bufs[:2])
    assert done.wait(30)
    assert all(not b.spilled for b in bufs[:2])
    with mgr.pinned_on_device(bufs[:2]):
        for b, p in zip(bufs[:2], payload[:2]):
            assert b.read(0, len(p)) == p
    for b in bufs:
        b.free()
    mgr.stop()


def test_climb_after_free_charges_nothing(tmp_path):
    """A restore racing free() (the prefetch pattern) must not charge
    budget for a buffer whose tiers were already torn down."""
    mgr = DeviceBufferManager(
        max_bytes=2 * MIN_BLOCK_SIZE, spill_dir=str(tmp_path)
    )
    a = mgr.stage_bytes(b"a" * 100)
    b = mgr.stage_bytes(b"b" * 100)
    c = mgr.stage_bytes(b"c" * 100)  # spills a
    assert a.spilled
    a.free()  # freed while spilled — tiers torn down
    before_dev, before_host = mgr.in_use_bytes, mgr.host_bytes
    a.ensure_device()  # the racing climb: must be a no-op
    assert a.array is None
    assert mgr.in_use_bytes == before_dev
    assert mgr.host_bytes == before_host
    done = mgr.prefetch([a, b])  # mixed dead/live set: completes
    assert done.wait(30)
    assert not b.spilled
    for buf in (b, c):
        buf.free()
    assert mgr.in_use_bytes == 0 and mgr.host_bytes == 0
    mgr.stop()


def test_pool_reuse_same_class():
    mgr = DeviceBufferManager()
    a = mgr.get(20_000)
    h = a.handle
    a.free()
    b = mgr.get(30_000)  # same 32 KiB class -> reused slab
    assert b.handle == h
    stats = mgr.stats()
    cls = _size_class(20_000)
    assert stats[cls]["total_alloc"] == 1
    assert stats[cls]["total_gets"] == 2
    b.free()
    mgr.stop()


def test_handle_table_resolution():
    mgr = DeviceBufferManager()
    buf = mgr.stage_bytes(b"registered")
    assert mgr.resolve(buf.handle) is buf
    buf.free()
    with pytest.raises(KeyError):
        mgr.resolve(buf.handle)
    mgr.stop()


def test_budget_enforced_device_residency():
    """The budget caps DEVICE residency: allocations beyond it demote
    LRU slabs to the host tier rather than failing."""
    mgr = DeviceBufferManager(max_bytes=MIN_BLOCK_SIZE * 2)
    a = mgr.get(1)
    b = mgr.get(1)
    c = mgr.get(1)  # over cap: a (LRU) demotes to host
    assert a.spilled
    assert mgr.in_use_bytes <= MIN_BLOCK_SIZE * 2
    a.free()
    b.free()
    c.free()
    assert mgr.in_use_bytes == 0
    mgr.stop()


def test_double_free_tolerated():
    mgr = DeviceBufferManager()
    buf = mgr.get(1)
    buf.free()
    buf.free()  # like RdmaCompletionListener.onFailure: reentry tolerated
    assert mgr.in_use_bytes == 0
    mgr.stop()


def test_budget_pressure_spills_lru_to_host():
    """SURVEY §7.3-4 tiering: over-budget allocation spills the
    least-recently-used live slab to host RAM instead of failing."""
    mgr = DeviceBufferManager(max_bytes=MIN_BLOCK_SIZE * 2)
    a = mgr.get(1)
    a.stage(b"oldest")
    b = mgr.get(1)
    b.stage(b"newer")
    c = mgr.get(1)  # budget full: LRU (a) must spill, not MemoryError
    assert a.spilled and not b.spilled and not c.spilled
    assert mgr.spill_count == 1
    assert a.read(0, 6) == b"oldest"  # readable from the host tier
    c.free()
    a.ensure_device()  # restore fits after c freed
    assert not a.spilled
    assert a.read(0, 6) == b"oldest"
    a.free()
    b.free()
    mgr.stop()


def test_restore_spills_someone_else():
    mgr = DeviceBufferManager(max_bytes=MIN_BLOCK_SIZE * 2)
    a = mgr.get(1); a.stage(b"aa")
    b = mgr.get(1); b.stage(b"bb")
    c = mgr.get(1); c.stage(b"cc")   # spills a
    assert a.spilled
    a.ensure_device()                 # must spill the new LRU (b)
    assert not a.spilled and b.spilled
    assert b.read(0, 2) == b"bb"
    for x in (a, b, c):
        x.free()
    mgr.stop()


def test_spilled_buffer_free_is_clean():
    mgr = DeviceBufferManager(max_bytes=MIN_BLOCK_SIZE)
    a = mgr.get(1); a.stage(b"x")
    b = mgr.get(1)   # spills a
    assert a.spilled
    a.free()         # freeing a spilled slab must not touch the budget
    assert mgr.in_use_bytes == b.capacity
    b.free()
    assert mgr.in_use_bytes == 0
    mgr.stop()


def test_nothing_spillable_raises():
    # cap smaller than one size class: no victim can ever make room
    mgr = DeviceBufferManager(max_bytes=MIN_BLOCK_SIZE // 2)
    with pytest.raises(MemoryError):
        mgr.get(1)
    mgr.stop()


def test_spill_of_freed_pooled_buffer_is_a_noop():
    """Race regression (caught by the threaded stress ~1-in-8 runs):
    _make_room picks its victim from the handle table WITHOUT holding
    any lock, so the victim can be free()d — and returned, array
    intact, to the pool stack — before its spill_to_host runs. The
    spill must then be a no-op: spilling a pooled slab released its
    device budget a SECOND time (in_use_bytes went negative) and left
    a tierless zombie in the pool."""
    from sparkrdma_tpu.ops.hbm_arena import MIN_BLOCK_SIZE, DeviceBufferManager

    mgr = DeviceBufferManager(max_bytes=4 * MIN_BLOCK_SIZE)
    try:
        buf = mgr.stage_bytes(b"y" * 100)
        assert mgr.in_use_bytes == MIN_BLOCK_SIZE
        buf.free()  # pooled: array kept, budget released, handle removed
        assert mgr.in_use_bytes == 0
        # the raced victim pick fires AFTER the free
        buf.spill_to_host()
        assert mgr.in_use_bytes == 0, "pooled slab's budget released twice"
        assert mgr.host_bytes == 0
        assert buf.array is not None and not buf.spilled, (
            "pooled slab was demoted to the host tier"
        )
        # the pooled slab is still perfectly reusable
        buf2 = mgr.stage_bytes(b"z" * 200)
        assert buf2 is buf  # LIFO pool reuse
        assert bytes(buf2.read(0, 200)) == b"z" * 200
        buf2.free()
        assert mgr.in_use_bytes == 0
    finally:
        mgr.stop()
