"""HBM slab pool tests — the device registered-memory plane.

Mirrors the buffer-pool property targets (reuse/leak accounting,
RdmaBufferManager.java:131-141; power-of-two size classing :103-118)."""

import pytest

from sparkrdma_tpu.ops.hbm_arena import (
    MIN_BLOCK_SIZE,
    DeviceBuffer,
    DeviceBufferManager,
    _size_class,
)


def test_size_class_rounding():
    assert _size_class(1) == MIN_BLOCK_SIZE
    assert _size_class(MIN_BLOCK_SIZE) == MIN_BLOCK_SIZE
    assert _size_class(MIN_BLOCK_SIZE + 1) == MIN_BLOCK_SIZE * 2
    assert _size_class(1 << 20) == 1 << 20


def test_stage_read_roundtrip():
    mgr = DeviceBufferManager()
    data = bytes(range(256)) * 100
    buf = mgr.stage_bytes(data)
    assert buf.length == len(data)
    assert buf.capacity >= len(data)
    assert buf.read() == data
    assert buf.read(16, 16) == data[16:32]
    buf.free()
    mgr.stop()


def test_pool_reuse_same_class():
    mgr = DeviceBufferManager()
    a = mgr.get(20_000)
    h = a.handle
    a.free()
    b = mgr.get(30_000)  # same 32 KiB class -> reused slab
    assert b.handle == h
    stats = mgr.stats()
    cls = _size_class(20_000)
    assert stats[cls]["total_alloc"] == 1
    assert stats[cls]["total_gets"] == 2
    b.free()
    mgr.stop()


def test_handle_table_resolution():
    mgr = DeviceBufferManager()
    buf = mgr.stage_bytes(b"registered")
    assert mgr.resolve(buf.handle) is buf
    buf.free()
    with pytest.raises(KeyError):
        mgr.resolve(buf.handle)
    mgr.stop()


def test_budget_enforced():
    mgr = DeviceBufferManager(max_bytes=MIN_BLOCK_SIZE * 2)
    a = mgr.get(1)
    b = mgr.get(1)
    with pytest.raises(MemoryError):
        mgr.get(1)
    a.free()
    c = mgr.get(1)  # freed capacity is available again
    b.free()
    c.free()
    mgr.stop()


def test_double_free_tolerated():
    mgr = DeviceBufferManager()
    buf = mgr.get(1)
    buf.free()
    buf.free()  # like RdmaCompletionListener.onFailure: reentry tolerated
    assert mgr.in_use_bytes == 0
    mgr.stop()
