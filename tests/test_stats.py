"""RemoteFetchHistogram / ShuffleReaderStats unit tests: bucket
boundaries, overflow, degenerate-shape guards, concurrency, and
snapshot/format consistency."""

import threading

from sparkrdma_tpu.locations import ShuffleManagerId
from sparkrdma_tpu.shuffle.stats import RemoteFetchHistogram, ShuffleReaderStats
from sparkrdma_tpu.utils.config import TpuShuffleConf


def test_bucket_boundaries():
    h = RemoteFetchHistogram(num_buckets=4, bucket_size_ms=10)
    h.add(0)      # bucket 0
    h.add(9.99)   # bucket 0
    h.add(10)     # bucket 1 (floor division)
    h.add(39.9)   # bucket 3 (last regular)
    assert h.snapshot() == [2, 1, 0, 1, 0]


def test_overflow_boundary():
    """Latency exactly at num_buckets * bucket_size_ms is the first
    value past the last regular bucket's range — it must land in the
    overflow bucket, and anything beyond stays there too."""
    h = RemoteFetchHistogram(num_buckets=4, bucket_size_ms=10)
    h.add(40)        # == 4 * 10 → overflow
    h.add(1_000_000)
    snap = h.snapshot()
    assert snap[:-1] == [0, 0, 0, 0]
    assert snap[-1] == 2


def test_negative_latency_clamps_to_first_bucket():
    """Clock skew can produce a negative latency; floor division would
    index a negative bucket (i.e. silently count as overflow via
    Python's negative indexing). It must count in bucket 0 instead."""
    h = RemoteFetchHistogram(num_buckets=4, bucket_size_ms=10)
    h.add(-5)
    h.add(-0.001)
    snap = h.snapshot()
    assert snap[0] == 2
    assert snap[-1] == 0


def test_degenerate_shapes_clamped():
    """bucket_size_ms <= 0 was a ZeroDivisionError in add(); both shape
    parameters clamp to 1 instead."""
    h = RemoteFetchHistogram(num_buckets=0, bucket_size_ms=0)
    h.add(0)
    h.add(100)
    assert h.num_buckets == 1
    assert h.bucket_size_ms == 1
    assert h.snapshot() == [1, 1]  # one regular bucket + overflow


def test_concurrent_add_conserves_count():
    h = RemoteFetchHistogram(num_buckets=8, bucket_size_ms=5)
    n_threads, per_thread = 8, 2000

    def work(seed):
        for i in range(per_thread):
            h.add((seed * 7 + i) % 60)  # spread across buckets + overflow

    threads = [threading.Thread(target=work, args=(t,)) for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sum(h.snapshot()) == n_threads * per_thread


def test_snapshot_format_consistency():
    h = RemoteFetchHistogram(num_buckets=3, bucket_size_ms=10)
    for ms in (1, 11, 12, 25, 99):
        h.add(ms)
    snap = h.snapshot()
    text = h.format()
    # one bracketed segment per bucket, counts in snapshot order
    segments = text.split("] ")
    assert len(segments) == len(snap)
    for seg, count in zip(segments, snap):
        assert seg.endswith(f": {count}") or seg.endswith(f": {count}]")
    # ranges cover [0, 30) then overflow
    assert "[0-10ms: 1]" in text
    assert "[10-20ms: 2]" in text
    assert "[20-30ms: 1]" in text
    assert "[>30ms: 1]" in text


def test_reader_stats_per_remote_and_registry_mirror():
    conf = TpuShuffleConf()
    stats = ShuffleReaderStats(conf)
    a = ShuffleManagerId("127.0.0.1", 1111, "exec-a")
    b = ShuffleManagerId("127.0.0.1", 2222, "exec-b")
    stats.update_remote_fetch_histogram(a, 3.0)
    stats.update_remote_fetch_histogram(a, 7.0)
    stats.update_remote_fetch_histogram(b, 5.0)
    snap = stats.snapshot()
    assert sum(snap["exec-a@127.0.0.1:1111"]) == 2
    assert sum(snap["exec-b@127.0.0.1:2222"]) == 1
    from sparkrdma_tpu.obs import get_registry

    reg_snap = get_registry().snapshot(prefix="reader.remote_fetch_ms")
    key = "reader.remote_fetch_ms{peer=exec-a}"
    assert reg_snap["histograms"][key]["count"] >= 2
