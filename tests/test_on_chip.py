"""On-chip test subset (VERDICT r4 missing #3): the device-path tests
that must hold on REAL TPU hardware, not only on the CPU farm.

Run: ``SRT_TPU_TESTS=1 python -m pytest tests -m tpu -q``
(conftest.py skips the CPU pin in that mode; the axon platform plugin
then provides the real chip). Under the normal CI run every test here
skips — the platform is pinned to CPU, which the whole rest of the
suite already covers.

The subset mirrors what bit round 3: flash forward AND backward
numerics (Mosaic-compiled kernels behave differently from the CPU
interpreter), the TeraSort step (device_sort + exchange), and the
typed stage_view path (host->HBM DMA with dtype reinterpretation).
First compile on the chip takes ~20-40 s per executable; shapes here
are kept small and few.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

tpu_only = pytest.mark.skipif(
    jax.devices()[0].platform == "cpu",
    reason="on-chip subset; run with SRT_TPU_TESTS=1 -m tpu",
)

pytestmark = [pytest.mark.tpu, tpu_only]


def test_flash_attention_forward_on_chip():
    from sparkrdma_tpu.ops.pallas_attention import flash_attention
    from sparkrdma_tpu.ops.ring_attention import reference_attention

    rng = np.random.default_rng(0)
    q, k, v = (
        jnp.asarray(rng.normal(size=(1, 256, 2, 64)).astype(np.float32))
        for _ in range(3)
    )
    out = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-2, rtol=2e-2
    )


def test_flash_attention_backward_on_chip():
    from sparkrdma_tpu.ops.pallas_attention import flash_attention
    from sparkrdma_tpu.ops.ring_attention import reference_attention

    rng = np.random.default_rng(1)
    q, k, v = (
        jnp.asarray(rng.normal(size=(1, 256, 2, 64)).astype(np.float32))
        for _ in range(3)
    )

    def loss_flash(q, k, v):
        return flash_attention(
            q, k, v, causal=True, block_q=128, block_k=128
        ).sum()

    def loss_ref(q, k, v):
        return reference_attention(q, k, v, causal=True).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-2, rtol=5e-2
        )


def test_terasort_step_on_chip():
    from sparkrdma_tpu.models import TeraSorter
    from sparkrdma_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(2)
    keys = rng.integers(0, 1 << 32, 1 << 14, dtype=np.uint32)
    sorter = TeraSorter(make_mesh(jax.devices()[:1]))
    out = sorter.sort(keys)
    np.testing.assert_array_equal(out, np.sort(keys))


def test_stage_view_typed_on_chip():
    from sparkrdma_tpu.ops.hbm_arena import DeviceBufferManager

    mgr = DeviceBufferManager()
    try:
        rng = np.random.default_rng(3)
        payload = rng.integers(0, 256, 64 * 1024, np.uint8).tobytes()
        buf = mgr.stage_view(memoryview(payload), len(payload), np.uint32)
        assert buf.array.dtype == jnp.uint32
        assert bytes(buf.read(0, len(payload))) == payload
        # sub-class valid length: tail masked by `length`, bytes exact
        short = payload[: 40_000]
        buf2 = mgr.stage_view(memoryview(short), len(short), np.uint32)
        assert bytes(buf2.read(0, len(short))) == short
        buf.free()
        buf2.free()
        assert mgr.in_use_bytes == 0
    finally:
        mgr.stop()


def test_exchange_single_device_on_chip():
    from sparkrdma_tpu.ops.exchange import ExchangeProgram, pack_blocks, unpack_blocks
    from sparkrdma_tpu.parallel.mesh import make_mesh

    prog = ExchangeProgram(make_mesh(jax.devices()[:1]))
    send, counts = pack_blocks([b"on-chip-block"], 64)
    recv, rcounts = prog.exchange(send, counts)
    assert unpack_blocks(np.asarray(recv), np.asarray(rcounts)) == [
        b"on-chip-block"
    ]
