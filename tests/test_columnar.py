"""Columnar zero-copy block format (DESIGN.md §25): codec roundtrip +
aliasing, frame interleaving, wire-extension roundtrip/legacy identity,
writer negotiation, e2e pickle↔columnar byte identity, and
collective-wave eligibility for ragged stages."""

import pickle
import struct

import numpy as np
import pytest

from sparkrdma_tpu.engine import serializer
from sparkrdma_tpu.engine.serializer import (
    CompressionCodec,
    frame_columnar,
    frame_compressed,
    iter_compressed_blocks,
)
from sparkrdma_tpu.locations import (
    BlockLocation,
    PartitionLocation,
    ShuffleManagerId,
)
from sparkrdma_tpu.obs import get_registry
from sparkrdma_tpu.shuffle import columnar
from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle, HashPartitioner
from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
from sparkrdma_tpu.utils.config import TpuShuffleConf


# ----------------------------------------------------------------------
# codec
# ----------------------------------------------------------------------
def test_magic_constant_pinned_to_serializer_copy():
    """The engine layer duplicates the magic (import-cycle firewall);
    this pin is the contract that keeps the copies equal."""
    assert serializer._COLUMNAR_MAGIC == columnar.MAGIC_BYTES
    assert struct.pack(">H", columnar.MAGIC) == columnar.MAGIC_BYTES


@pytest.mark.parametrize(
    "dtypes",
    [
        (np.uint32,),
        (np.uint32, np.int64),
        (np.uint8, np.float32, np.float64),
        (np.int16, np.uint16, np.bool_),
        (np.int8, np.uint64, np.int32, np.float64),
    ],
)
def test_batch_roundtrip_property(dtypes):
    """Random typed batches: encode_batch -> iter_records reproduces
    every row with identical values AND dtypes — the byte-identity
    contract with the pickle path."""
    rng = np.random.default_rng(42)
    rows = 257  # deliberately not a multiple of anything
    cols = []
    for dt in dtypes:
        dt = np.dtype(dt)
        if dt == np.bool_:
            cols.append(rng.integers(0, 2, rows).astype(dt))
        elif dt.kind == "f":
            cols.append(rng.standard_normal(rows).astype(dt))
        else:
            info = np.iinfo(dt)
            cols.append(
                rng.integers(info.min, int(info.max) + 1, rows, dtype=dt)
            )
    records = [tuple(c[i] for c in cols) for i in range(rows)]
    payload = columnar.encode_batch(records)
    assert payload is not None
    decoded = list(columnar.iter_records(payload))
    assert len(decoded) == rows
    for orig, got in zip(records, decoded):
        for a, b in zip(orig, got):
            assert a.dtype == b.dtype
            assert a == b or (a != a and b != b)  # NaN-safe equality
    # the framed length is always a multiple of 8 — the collective
    # eligibility invariant
    assert (4 + len(payload)) % 8 == 0


def test_decode_aliases_buffer_zero_copy():
    """Decoded columns ALIAS the frame buffer: no per-block heap copy.
    Proven two ways — np.shares_memory against the byte view, and a
    mutation through the backing bytearray observed in the column."""
    keys = np.arange(100, dtype=np.uint32)
    vals = np.arange(100, dtype=np.float64) * 1.5
    frame = bytearray(columnar.encode_columns([keys, vals]))
    view = memoryview(frame)
    cols = columnar.decode_columns(view)
    base = np.frombuffer(view, dtype=np.uint8)
    for col in cols:
        assert np.shares_memory(col, base)
    # mutate the first key's little-endian low byte through the buffer
    off = columnar._COL.unpack_from(view, columnar._HDR.size)[1]
    frame[off] = 0x7F
    assert cols[0][0] == 0x7F  # the view observed it: same memory


def test_nonconforming_batches_fall_back():
    u = np.uint32(1)
    assert columnar.encode_batch([]) is None
    assert columnar.encode_batch([(1, 2)]) is None  # python ints
    assert columnar.encode_batch([("k", u)]) is None  # string key
    assert columnar.encode_batch([[u, u]]) is None  # list, not tuple
    assert columnar.encode_batch([(u, u), (u,)]) is None  # ragged arity
    assert columnar.encode_batch([(u,), (np.int64(1),)]) is None  # mixed
    assert columnar.encode_batch([(np.str_("x"),)]) is None  # non-fixed
    assert columnar.encode_batch([(u, np.int64(2))]) is not None


def test_decode_rejects_corrupt_headers():
    frame = bytearray(columnar.encode_columns([np.arange(8, dtype=np.uint32)]))
    bad_magic = bytearray(frame)
    bad_magic[0] ^= 0xFF
    with pytest.raises(ValueError):
        columnar.decode_columns(bad_magic)
    bad_version = bytearray(frame)
    bad_version[2] ^= 0xFF
    with pytest.raises(ValueError):
        columnar.decode_columns(bad_version)
    truncated = frame[: columnar._HDR.size - 1]
    with pytest.raises(ValueError):
        columnar.decode_columns(bytes(truncated))


def test_interleaved_frames_in_one_stream():
    """Columnar and pickle frames interleave freely inside one block
    stream; iter_compressed_blocks sniffs the magic per frame."""
    import io

    codec = CompressionCodec(enabled=True)
    col_payload = columnar.encode_batch(
        [(np.uint32(i), np.int64(i * 2)) for i in range(10)]
    )
    pkl_raw = b"".join(
        struct.pack(">I", len(d)) + d
        for d in (pickle.dumps(("k", i)) for i in range(3))
    )
    stream = io.BytesIO(
        frame_columnar(col_payload)
        + frame_compressed(codec, pkl_raw)
        + frame_columnar(col_payload)
    )
    blocks = list(iter_compressed_blocks(stream, codec))
    assert len(blocks) == 3
    assert columnar.is_columnar(blocks[0])
    assert not columnar.is_columnar(blocks[1])
    assert columnar.is_columnar(blocks[2])
    assert len(list(columnar.iter_records(blocks[0]))) == 10


# ----------------------------------------------------------------------
# wire extension (0xFFF9)
# ----------------------------------------------------------------------
def _mk_loc(pid, length, fmt=0):
    return PartitionLocation(
        ShuffleManagerId("host", 4321, f"exec-{pid % 2}"),
        pid,
        BlockLocation(pid * 64, length, 7, block_format=fmt),
    )


@pytest.mark.parametrize("seg_size", [4096, 256])
def test_format_extension_roundtrip(seg_size):
    from sparkrdma_tpu.rpc import PublishPartitionLocationsMsg, RpcMsg

    locs = [
        _mk_loc(p, 1000 + p, fmt=(BlockLocation.FORMAT_COLUMNAR if p % 3 else 0))
        for p in range(40)
    ]
    msg = PublishPartitionLocationsMsg(5, -1, locs, num_map_outputs=1)
    got = []
    for seg in msg.to_segments(seg_size):
        got.extend(RpcMsg.parse_segment(bytes(seg)).locations)
    assert len(got) == len(locs)
    for orig, back in zip(locs, got):
        assert back.block.block_format == orig.block.block_format
        assert back.block.is_columnar == (orig.block.block_format == 1)


def test_format_extension_absent_keeps_legacy_bytes():
    """All-pickle location sets emit NO 0xFFF9 group — frames are
    byte-identical to pre-§25 builds."""
    from sparkrdma_tpu.rpc import PublishPartitionLocationsMsg

    locs = [_mk_loc(p, 500 + p) for p in range(10)]
    msg = PublishPartitionLocationsMsg(5, -1, locs, num_map_outputs=1)
    payload = b"".join(bytes(s) for s in msg.to_segments(1 << 20))
    assert b"\xff\xf9" not in payload


# ----------------------------------------------------------------------
# writer negotiation
# ----------------------------------------------------------------------
def _np_records(n, num_keys=97):
    return [
        (np.uint32(i % num_keys), np.int64(i * 3)) for i in range(n)
    ]


def test_columnar_partition_writer_batches_and_fallback():
    from sparkrdma_tpu.shuffle.writer.columnar import ColumnarPartitionWriter

    out = []
    codec = CompressionCodec(enabled=True)
    w = ColumnarPartitionWriter(codec, out.append, batch_rows=8)
    for rec in _np_records(20):
        w.write_record(rec)
    w.write_record(("python", "tuple"))  # poisons the tail batch
    w.flush_batch()
    assert w.columnar_frames == 2  # two full batches of 8
    assert w.pickle_fallbacks == 1  # the mixed remainder
    assert not w.all_columnar


def test_sort_file_auto_negotiation(tmp_path):
    from sparkrdma_tpu.shuffle.writer.sort_file import write_sorted_file

    codec = CompressionCodec(enabled=True)
    handle = BaseShuffleHandle(
        shuffle_id=0, num_maps=1, partitioner=HashPartitioner(3)
    )
    # np-scalar tuples: auto engages columnar, every partition tagged
    res = write_sorted_file(
        iter(_np_records(1000)), handle, codec, str(tmp_path / "a.tmp"),
        block_format="auto", batch_rows=64,
    )
    assert all(f == BlockLocation.FORMAT_COLUMNAR for f in res.formats)
    assert res.columnar_frames > 0 and res.pickle_fallbacks == 0
    assert all(n % 8 == 0 for n in res.lengths if n)
    # python tuples: auto stays pickle, byte-identical to forced pickle
    legacy = [(f"k{i % 7}", i) for i in range(500)]
    res_auto = write_sorted_file(
        iter(legacy), handle, codec, str(tmp_path / "b.tmp"),
        block_format="auto",
    )
    res_pickle = write_sorted_file(
        iter(legacy), handle, codec, str(tmp_path / "c.tmp"),
        block_format="pickle",
    )
    assert res_auto.formats == [0, 0, 0]
    assert res_auto.columnar_frames == 0
    assert (tmp_path / "b.tmp").read_bytes() == (
        tmp_path / "c.tmp"
    ).read_bytes()


# ----------------------------------------------------------------------
# e2e byte identity: the same job under columnar and pickle
# ----------------------------------------------------------------------
def _run_cluster_shuffle(block_format, records_per_map=2000):
    conf = TpuShuffleConf(
        {
            "tpu.shuffle.shuffleWriteMethod": "wrapper",
            "tpu.shuffle.block.format": block_format,
            "tpu.shuffle.block.columnarBatchRows": "256",
        }
    )
    driver = TpuShuffleManager(conf, is_driver=True)
    ex0 = TpuShuffleManager(conf, is_driver=False, executor_id="col-0")
    ex1 = TpuShuffleManager(conf, is_driver=False, executor_id="col-1")
    try:
        handle = BaseShuffleHandle(
            shuffle_id=0, num_maps=2, partitioner=HashPartitioner(3)
        )
        driver.register_shuffle(handle)
        for map_id, ex in [(0, ex0), (1, ex1)]:
            recs = [
                (np.uint32((map_id * 7919 + i) % 997), np.int64(i))
                for i in range(records_per_map)
            ]
            w = ex.get_writer(handle, map_id)
            w.write(iter(recs))
            assert w.stop(True) is not None
        ex0.finalize_maps(0)
        ex1.finalize_maps(0)
        got = []
        for ex, (lo, hi) in [(ex0, (0, 2)), (ex1, (2, 3))]:
            got.extend(ex.get_reader(handle, lo, hi).read())
        return got
    finally:
        ex1.stop()
        ex0.stop()
        driver.stop()


def test_e2e_byte_identity_columnar_vs_pickle():
    """Acceptance: the same shuffle under forced columnar and forced
    pickle delivers byte-identical rows (values AND dtypes), and the
    columnar run actually exercised the view-decode path."""
    reg = get_registry()
    before = reg.snapshot(prefix="block.")
    rows_col = _run_cluster_shuffle("columnar")
    delta = reg.delta(before, prefix="block.")
    counters = delta.get("counters", {})
    assert any(
        k.startswith("block.view_decodes") and v > 0
        for k, v in counters.items()
    ), f"columnar run never hit the view-decode path: {counters}"
    assert any(
        k.startswith("block.columnar_blocks") and v > 0
        for k, v in counters.items()
    )
    rows_pkl = _run_cluster_shuffle("pickle")
    key = lambda r: (int(r[0]), int(r[1]))  # noqa: E731
    rows_col.sort(key=key)
    rows_pkl.sort(key=key)
    assert len(rows_col) == len(rows_pkl) == 4000
    for a, b in zip(rows_col, rows_pkl):
        assert type(a[0]) is type(b[0]) and a[0] == b[0]
        assert type(a[1]) is type(b[1]) and a[1] == b[1]
    assert pickle.dumps(rows_col) == pickle.dumps(rows_pkl)


# ----------------------------------------------------------------------
# collective eligibility: ragged pickle vs padded columnar
# ----------------------------------------------------------------------
def test_ragged_stage_becomes_wave_eligible_under_columnar():
    """Acceptance: a ragged stage (odd block lengths, as pickle payloads
    produce) is 0% wave-eligible at a 4-byte elem dtype; the same stage
    with columnar-padded lengths (every framed block a multiple of 8 by
    construction) is >=90% eligible and compiles into DMA waves."""
    from sparkrdma_tpu.shuffle import device_fetch as df
    from sparkrdma_tpu.shuffle.collective import ShuffleScheduleCompiler
    from sparkrdma_tpu.shuffle.device_io import DeviceShuffleIO

    BLOCK = 64 << 10
    conf = TpuShuffleConf({"tpu.shuffle.transport": "python"})
    driver = TpuShuffleManager(conf, is_driver=True)
    ex_map = TpuShuffleManager(conf, is_driver=False, executor_id="cb-map")
    ex_red = TpuShuffleManager(conf, is_driver=False, executor_id="cb-red")
    io_map, io_red = DeviceShuffleIO(ex_map), DeviceShuffleIO(ex_red)
    lanes = [f"cb-lane-{i}" for i in range(3)]
    for lane in lanes:
        df.register_arena(lane, io_map.device_buffers)
    try:
        comp = ShuffleScheduleCompiler(conf, io_red.device_buffers, "cb-red")

        def loc(pid, length, lane):
            return PartitionLocation(
                ShuffleManagerId("host", 1234, lane),
                pid,
                BlockLocation(
                    0, length, 1, device_coords=0, arena_handle=1
                ),
            )

        # ragged pickle stage: 12 blocks, every length odd
        ragged = [
            loc(p, BLOCK + 1 + 2 * i, lanes[i % 3])
            for i in range(4)
            for p in range(3)
        ]
        plan = comp.plan(ragged, dtype=np.uint32)
        assert plan.device_blocks == 0
        assert len(plan.passthrough) == len(ragged)
        assert not plan.waves

        # the same stage with columnar lengths: multiples of 8 (the
        # codec's framing invariant, test_batch_roundtrip_property)
        padded = [
            loc(p, BLOCK + 8 * (1 + i), lanes[i % 3])
            for i in range(4)
            for p in range(3)
        ]
        plan = comp.plan(padded, dtype=np.uint32)
        eligible_frac = plan.device_blocks / len(padded)
        assert eligible_frac >= 0.9, (
            f"only {plan.device_blocks}/{len(padded)} wave-eligible"
        )
        assert plan.waves
        # uint64 elems too: columnar pads to 8, not just 4
        assert comp.plan(padded, dtype=np.uint64).device_blocks == len(
            padded
        )
    finally:
        for lane in lanes:
            df.unregister_arena(lane, io_map.device_buffers)
        io_red.stop()
        io_map.stop()
        ex_red.stop()
        ex_map.stop()
        driver.stop()


# ----------------------------------------------------------------------
# device consume
# ----------------------------------------------------------------------
def test_device_put_columns_and_columnar_sort():
    from sparkrdma_tpu.models.terasort import MapShardSorter
    from sparkrdma_tpu.ops.sort import device_put_columns

    rng = np.random.default_rng(11)
    keys = rng.integers(0, 2**32, 2048, dtype=np.uint32)
    vals = np.arange(2048, dtype=np.int64)
    frame = columnar.encode_columns([keys, vals])
    cols = device_put_columns(frame)
    assert len(cols) == 2
    # (int64 narrows to int32 under jax's default x64-disabled config;
    # the key column's uint32 survives exactly)
    assert np.asarray(cols[0]).dtype == np.dtype(np.uint32)
    np.testing.assert_array_equal(np.asarray(cols[0]), keys)
    np.testing.assert_array_equal(np.asarray(cols[1]), vals)
    sorter = MapShardSorter()
    edges = np.asarray([1 << 30, 1 << 31, 3 << 30], dtype=np.uint32)
    s1, b1 = sorter.sort_partition(keys, edges)
    s2, b2 = sorter.sort_columnar_partition(frame, edges)
    np.testing.assert_array_equal(s1, s2)
    np.testing.assert_array_equal(b1, b2)
