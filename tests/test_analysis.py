"""Fixture tests for the invariant analysis suite.

Each lint pass gets a planted violation in a synthetic SourceFile and
must report exactly that plant; the lock-order detector gets a
synthetic AB/BA cycle, a same-name nesting, and a sleep-under-hot-lock,
each on a private detector instance so the process-wide default (armed
by SPARKRDMA_LOCK_ORDER=1) keeps watching the real tree undisturbed.
The suite ends with the tree-clean assertion the CI ``analysis`` job
gates on.

Planted sources are built with string concatenation where a literal
would otherwise trip the passes (or the suppression scanner) on THIS
file when the CLI lints the tests/ directory.
"""

from __future__ import annotations

import textwrap
import time

from sparkrdma_tpu.analysis import (
    PASS_IDS,
    SourceFile,
    load_tree,
    repo_root,
    run_passes,
)
from sparkrdma_tpu.analysis.lockorder import LockOrderDetector, named_lock

ROOT = repo_root()

# assembled so the knob pass / suppression scanner never match literals
# in this test file itself
_KNOB_PREFIX = "tpu." + "shuffle."
_SUPPRESS = "# analysis: " + "ignore"


def _findings(source_file, pass_id):
    return run_passes([source_file], ROOT, only=[pass_id])


# -- knob-registry ---------------------------------------------------------


def test_knob_pass_catches_planted_typo():
    sf = SourceFile(
        "tests/fake_knob_user.py",
        f'K = "{_KNOB_PREFIX}fetch.bogus_typo_knob"\n',
    )
    found = _findings(sf, "knob-registry")
    assert len(found) == 1
    assert found[0].pass_id == "knob-registry"
    assert "bogus_typo_knob" in found[0].message
    assert found[0].line == 1


def test_knob_pass_accepts_declared_key():
    sf = SourceFile(
        "tests/fake_knob_user.py",
        f'K = "{_KNOB_PREFIX}recvQueueDepth"\n',
    )
    assert _findings(sf, "knob-registry") == []


# -- metric-families -------------------------------------------------------


def test_metric_pass_catches_label_mismatch():
    sf = SourceFile(
        "sparkrdma_tpu/fake_metrics_user.py",
        'c = reg.counter("mempool.hits", bogus_label="x")\n',
    )
    found = _findings(sf, "metric-families")
    assert len(found) == 1
    assert "label set" in found[0].message
    assert "bogus_label" in found[0].message


def test_metric_pass_catches_undeclared_family_and_wrong_kind():
    sf = SourceFile(
        "sparkrdma_tpu/fake_metrics_user.py",
        textwrap.dedent(
            """\
            a = reg.counter("no.such_family_xyz")
            b = reg.gauge("mempool.hits")
            """
        ),
    )
    found = _findings(sf, "metric-families")
    assert len(found) == 2
    assert "not in METRIC_FAMILIES" in found[0].message
    assert "declared as a counter" in found[1].message


def test_metric_pass_ignores_test_tree_and_registry_module():
    bad = 'c = reg.counter("no.such_family_xyz")\n'
    for path in ("tests/fake.py", "sparkrdma_tpu/obs/metrics.py"):
        assert _findings(SourceFile(path, bad), "metric-families") == []


# -- wire-markers ----------------------------------------------------------

_WIRE_TEMPLATE = """\
import struct


class Codec:
    _EXT_HDR = struct.Struct(">HI")
    _DEV_MARKER = {marker}
    _DEV_ITEM = struct.Struct(">II")

    def to_bytes(self):
        return self._EXT_HDR.pack(self._DEV_MARKER, 1) + self._DEV_ITEM.pack(1, 2)

    def from_bytes(self, b):
        {parser_body}
"""


# a parser that satisfies the ordering invariant: one while peek loop
# dispatching every marker, each branch ending in `continue`
_LOOPED_PARSER = """\
i = 0
        while i < len(b):
            m = self._EXT_HDR.unpack_from(b, i)[0]
            if m == self._DEV_MARKER:
                self._DEV_ITEM.unpack_from(b, i + 6)
                i += 14
                continue
            break
        return i"""


def test_wire_pass_catches_low_marker_value():
    src = _WIRE_TEMPLATE.format(marker="0x0010", parser_body=_LOOPED_PARSER)
    found = _findings(SourceFile("sparkrdma_tpu/fake_rpc.py", src), "wire-markers")
    assert len(found) == 1
    assert "0xFF00" in found[0].message


def test_wire_pass_catches_one_sided_extension():
    src = _WIRE_TEMPLATE.format(
        marker="0xFF10",
        parser_body="return self._EXT_HDR.unpack_from(b)",
    )
    found = _findings(SourceFile("sparkrdma_tpu/fake_rpc.py", src), "wire-markers")
    assert found, "parser never touches _DEV_MARKER/_DEV_ITEM"
    assert all("parser" in f.message for f in found)
    assert any("one-sided" in f.message for f in found)


def test_wire_pass_clean_fixture_and_path_scoping():
    src = _WIRE_TEMPLATE.format(marker="0xFF10", parser_body=_LOOPED_PARSER)
    assert _findings(SourceFile("sparkrdma_tpu/fake_rpc.py", src), "wire-markers") == []
    # the same planted breakage outside *rpc.py/*locations.py is out of scope
    bad = _WIRE_TEMPLATE.format(marker="0x0010", parser_body="return b")
    assert _findings(SourceFile("sparkrdma_tpu/fake_other.py", bad), "wire-markers") == []


def test_wire_pass_ordering_requires_peek_loop():
    # marker dispatched straight-line (no while loop): parse order is fixed
    src = _WIRE_TEMPLATE.format(
        marker="0xFF10",
        parser_body="return self._EXT_HDR, self._DEV_MARKER, self._DEV_ITEM",
    )
    found = _findings(SourceFile("sparkrdma_tpu/fake_rpc.py", src), "wire-markers")
    assert len(found) == 1
    assert "peek loop" in found[0].message


def test_wire_pass_ordering_requires_continue_per_branch():
    # loop dispatches the marker but the branch falls through instead of
    # re-peeking: every extension after it parses order-dependently
    body = _LOOPED_PARSER.replace("                continue\n", "")
    src = _WIRE_TEMPLATE.format(marker="0xFF10", parser_body=body)
    found = _findings(SourceFile("sparkrdma_tpu/fake_rpc.py", src), "wire-markers")
    assert len(found) == 1
    assert "continue" in found[0].message


# -- tenant-scope ----------------------------------------------------------


def test_tenant_pass_catches_unscoped_spawn():
    src = textwrap.dedent(
        """\
        import threading


        def _worker():
            return 1


        def spawn():
            t = threading.Thread(target=_worker, daemon=True)
            t.start()
        """
    )
    found = _findings(SourceFile("sparkrdma_tpu/shuffle/fake_spawn.py", src), "tenant-scope")
    assert len(found) == 1
    assert "_worker" in found[0].message
    assert "tenant_scope" in found[0].message


def test_tenant_pass_accepts_scoped_closure_and_reentering_target():
    src = textwrap.dedent(
        """\
        import threading

        from sparkrdma_tpu import tenancy
        from sparkrdma_tpu.tenancy import tenant_scope


        def _retry(tenant):
            with tenant_scope(tenant):
                return 1


        def spawn(tenant, fn):
            threading.Thread(target=tenancy.scoped(tenant, fn)).start()
            threading.Timer(0.1, _retry).start()
        """
    )
    assert _findings(SourceFile("sparkrdma_tpu/shuffle/fake_spawn.py", src), "tenant-scope") == []


# -- suppression syntax ----------------------------------------------------


def test_bare_suppression_is_itself_a_finding():
    sf = SourceFile(
        "tests/fake_knob_user.py",
        f'K = "{_KNOB_PREFIX}fetch.bogus_typo_knob"  {_SUPPRESS}[knob-registry]\n',
    )
    found = _findings(sf, "knob-registry")
    # the knob finding survives AND the reasonless ignore is reported
    assert {f.pass_id for f in found} == {"knob-registry", "suppression"}
    assert any("requires a ': <reason>'" in f.message for f in found)


def test_reasoned_suppression_silences_the_finding():
    sf = SourceFile(
        "tests/fake_knob_user.py",
        f'K = "{_KNOB_PREFIX}fetch.bogus_typo_knob"  '
        f"{_SUPPRESS}[knob-registry]: fixture for the docs example\n",
    )
    assert _findings(sf, "knob-registry") == []


def test_comment_line_suppression_covers_next_line():
    sf = SourceFile(
        "tests/fake_knob_user.py",
        f"{_SUPPRESS}[all]: fixture for the docs example\n"
        f'K = "{_KNOB_PREFIX}fetch.bogus_typo_knob"\n',
    )
    assert _findings(sf, "knob-registry") == []


def test_unknown_pass_id_in_suppression_is_reported():
    sf = SourceFile(
        "tests/fake_knob_user.py",
        f"x = 1  {_SUPPRESS}[no-such-pass]: whatever\n",
    )
    found = run_passes([sf], ROOT, only=["knob-registry"])
    assert len(found) == 1
    assert found[0].pass_id == "suppression"
    assert "unknown pass id" in found[0].message


# -- lock-order detector ---------------------------------------------------


def test_detector_flags_ab_ba_cycle():
    det = LockOrderDetector()
    a = named_lock("t.cycle.A", detector=det)
    b = named_lock("t.cycle.B", detector=det)
    det.enable()
    try:
        with a:
            with b:
                pass
        with b:
            with a:  # closes the cycle — flagged without a real deadlock
                pass
    finally:
        det.disable()
    assert any("lock-order cycle" in v for v in det.violations)
    assert any("t.cycle.A" in v and "t.cycle.B" in v for v in det.violations)


def test_detector_consistent_order_is_clean():
    det = LockOrderDetector()
    a = named_lock("t.ord.A", detector=det)
    b = named_lock("t.ord.B", detector=det)
    det.enable()
    try:
        for _ in range(3):
            with a:
                with b:
                    pass
    finally:
        det.disable()
    assert det.violations == []
    assert det.edges == {"t.ord.A": {"t.ord.B"}}


def test_detector_flags_same_name_nesting_unless_opted_in():
    det = LockOrderDetector()
    l1 = named_lock("t.pair", detector=det)
    l2 = named_lock("t.pair", detector=det)
    det.enable()
    try:
        with l1:
            with l2:
                pass
    finally:
        det.disable()
    assert any("same-name lock nesting" in v for v in det.violations)

    det2 = LockOrderDetector()
    m1 = named_lock("t.pair2", allow_self_nest=True, detector=det2)
    m2 = named_lock("t.pair2", allow_self_nest=True, detector=det2)
    det2.enable()
    try:
        with m1:
            with m2:
                pass
    finally:
        det2.disable()
    assert det2.violations == []


def test_detector_flags_sleep_under_hot_lock():
    det = LockOrderDetector()
    hot = named_lock("t.hotpath", hot=True, detector=det)
    cold = named_lock("t.coldpath", detector=det)
    det.enable()
    try:
        with cold:
            time.sleep(0)  # cold lock: allowed
        with hot:
            time.sleep(0)  # hot lock: flagged
    finally:
        det.disable()
    assert len([v for v in det.violations if "time.sleep" in v]) == 1
    assert any("t.hotpath" in v for v in det.violations)


def test_detector_recursive_reacquire_is_not_self_nesting():
    det = LockOrderDetector()
    r = named_lock("t.rec", recursive=True, detector=det)
    det.enable()
    try:
        with r:
            with r:
                pass
    finally:
        det.disable()
    assert det.violations == []


def test_disabled_detector_records_nothing():
    det = LockOrderDetector()
    a = named_lock("t.off.A", detector=det)
    b = named_lock("t.off.B", detector=det)
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    assert det.edges == {}
    assert det.violations == []


# -- whole-tree gate -------------------------------------------------------


def test_cli_lists_all_passes():
    from sparkrdma_tpu.analysis.__main__ import main

    assert main(["--list"]) == 0
    assert set(PASS_IDS) == {
        "knob-registry",
        "metric-families",
        "wire-markers",
        "tenant-scope",
    }


def test_tree_is_clean():
    """The committed tree carries zero unsuppressed findings — the same
    invariant the CI ``analysis`` job enforces via the CLI."""
    files = load_tree(ROOT)
    assert len(files) > 50  # sanity: the walk actually found the tree
    findings = run_passes(files, ROOT)
    assert findings == [], "\n" + "\n".join(f.render() for f in findings)
