"""Unified observability layer: metrics registry units, span tracer
semantics, and the tier-1 e2e — a real cluster shuffle whose exported
Chrome trace carries one trace id across driver and executor roles,
with registry counters populated from every instrumented layer."""

import json
import subprocess
import sys
import threading

import pytest

from sparkrdma_tpu.obs import (
    MetricsRegistry,
    Tracer,
    get_registry,
    metric_key,
    mint_trace_id,
    to_chrome_trace,
)


# ---------------------------------------------------------------------------
# registry units (fresh instances — the global registry belongs to e2e)
# ---------------------------------------------------------------------------

def test_metric_key_canonical():
    assert metric_key("a.b", {}) == "a.b"
    assert metric_key("a.b", {"z": "1", "a": "2"}) == "a.b{a=2,z=1}"


def test_counter_get_or_create_and_inc():
    reg = MetricsRegistry()
    c1 = reg.counter("x.sends", role="e0")
    c2 = reg.counter("x.sends", role="e0")
    assert c1 is c2
    c1.inc()
    c1.inc(41)
    assert reg.snapshot()["counters"]["x.sends{role=e0}"] == 42


def test_kind_mismatch_raises():
    reg = MetricsRegistry()
    reg.counter("m")
    with pytest.raises(TypeError):
        reg.gauge("m")


def test_gauge_tracks_high_water_mark():
    reg = MetricsRegistry()
    g = reg.gauge("x.in_use")
    g.add(100)
    g.add(200)
    g.add(-250)
    snap = reg.snapshot()["gauges"]["x.in_use"]
    assert snap == {"value": 50, "hwm": 300}


def test_histogram_buckets_and_overflow():
    reg = MetricsRegistry()
    h = reg.histogram("x.ms", bounds=(1, 10, 100))
    for v in (0.5, 1.0, 9, 100, 101, 5000):
        h.observe(v)
    snap = reg.snapshot()["histograms"]["x.ms"]
    assert snap["count"] == 6
    assert snap["min"] == 0.5 and snap["max"] == 5000
    # bounds are inclusive upper edges; 1.0 -> le_1, 100 -> le_100
    assert snap["buckets"] == {"le_1": 2, "le_10": 1, "le_100": 1, "overflow": 2}


def test_snapshot_match_includes_unlabeled():
    """Role-filtered views keep process-global metrics (no role label)
    but exclude other roles'."""
    reg = MetricsRegistry()
    reg.counter("a.n", role="e0").inc()
    reg.counter("a.n", role="e1").inc()
    reg.counter("b.global").inc()
    snap = reg.snapshot(match={"role": "e0"})
    assert set(snap["counters"]) == {"a.n{role=e0}", "b.global"}


def test_delta_diffs_counters_and_histograms():
    reg = MetricsRegistry()
    c = reg.counter("d.n")
    h = reg.histogram("d.ms", bounds=(10,))
    c.inc(5)
    h.observe(3)
    prev = reg.snapshot()
    c.inc(7)
    h.observe(4)
    d = reg.delta(prev)
    assert d["counters"]["d.n"] == 7
    assert d["histograms"]["d.ms"]["count"] == 1
    assert d["histograms"]["d.ms"]["sum"] == pytest.approx(4.0)


def test_delta_after_reset_does_not_resurrect_totals():
    """Registry lifecycle for long-lived hubs (ISSUE 5 satellite): a
    moving-baseline delta taken across a reset() must apply the
    counter-reset rule — restart from the current value — instead of
    going negative or replaying pre-reset totals."""
    reg = MetricsRegistry()
    c = reg.counter("r.n")
    h = reg.histogram("r.ms", bounds=(10,))
    c.inc(5)
    h.observe(3)
    h.observe(7)
    prev = reg.snapshot()  # moving baseline: 5 / count 2
    reg.reset()
    c.inc(2)
    h.observe(1)
    d = reg.delta(prev)
    assert d["counters"]["r.n"] == 2
    assert d["histograms"]["r.ms"]["count"] == 1
    assert d["histograms"]["r.ms"]["sum"] == pytest.approx(1.0)
    # and the next interval, with the baseline advanced, diffs normally
    prev = reg.snapshot()
    c.inc(3)
    assert reg.delta(prev)["counters"]["r.n"] == 3


def test_registry_concurrent_get_or_create_and_inc():
    reg = MetricsRegistry()
    n_threads, per_thread = 8, 500

    def work():
        for i in range(per_thread):
            reg.counter("c.n", k=str(i % 5)).inc()

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = reg.snapshot()["counters"]
    assert sum(snap.values()) == n_threads * per_thread
    assert len(snap) == 5


def test_to_json_round_trips():
    reg = MetricsRegistry()
    reg.counter("j.n", role="r").inc(3)
    doc = json.loads(reg.to_json(indent=1))
    assert doc["counters"]["j.n{role=r}"] == 3


# ---------------------------------------------------------------------------
# tracer units
# ---------------------------------------------------------------------------

def test_mint_trace_id_nonzero_63bit():
    for _ in range(100):
        t = mint_trace_id()
        assert 0 < t < (1 << 63)


def test_span_nesting_and_parent_ids():
    tr = Tracer(role="t-nest")
    with tr.span("outer", trace_id=7) as outer:
        with tr.span("inner") as inner:
            pass
    spans = {s.name: s for s in tr.spans()}
    assert spans["inner"].parent_id == spans["outer"].span_id
    # inner had no explicit id/binding: inherits the parent's trace
    assert spans["inner"].trace_id == 7
    assert spans["outer"].trace_id == 7
    assert spans["outer"].end >= spans["inner"].end >= spans["inner"].start


def test_binding_resolves_open_span_at_close():
    """The executor pattern: a span opens before the trace id arrives
    on the wire; the binding lands while it is open and the span still
    resolves it at close time."""
    tr = Tracer(role="t-bind")
    with tr.span("fetch", shuffle_id=3):
        tr.bind_shuffle(3, 99)
    assert tr.spans()[0].trace_id == 99


def test_disabled_tracer_records_nothing():
    tr = Tracer(role="t-off", enabled=False)
    with tr.span("x"):
        pass
    tr.record("y", 0.0, 1.0)
    assert tr.spans() == []


def test_max_spans_bounds_memory():
    tr = Tracer(role="t-cap", max_spans=100)
    for i in range(250):
        tr.record("s", float(i), float(i))
    spans = tr.spans()
    assert len(spans) == 100
    assert spans[0].start == 150.0  # oldest dropped


def test_chrome_trace_format():
    tr = Tracer(role="t-fmt")
    with tr.span("work", trace_id=0xAB, foo="bar"):
        pass
    doc = to_chrome_trace([tr])
    assert doc["displayTimeUnit"] == "ms"
    meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert meta[0]["args"]["name"] == "t-fmt"
    ev = [e for e in doc["traceEvents"] if e["ph"] == "X"][0]
    assert ev["name"] == "work"
    assert ev["dur"] >= 0
    assert ev["args"]["trace_id"] == "0xab"
    assert ev["args"]["foo"] == "bar"
    json.dumps(doc)  # must be JSON-serializable as-is


# ---------------------------------------------------------------------------
# tier-1 e2e: cluster shuffle -> registry counters + cross-role trace
# ---------------------------------------------------------------------------

def test_cluster_shuffle_trace_and_registry(tmp_path):
    from sparkrdma_tpu.obs import export_chrome_trace
    from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle, HashPartitioner
    from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
    from sparkrdma_tpu.utils.config import TpuShuffleConf

    conf = TpuShuffleConf(
        {
            "tpu.shuffle.shuffleWriteMethod": "wrapper",
            "tpu.shuffle.shuffleWriteBlockSize": "65536",
            "tpu.shuffle.shuffleReadBlockSize": "65536",
        }
    )
    driver = TpuShuffleManager(conf, is_driver=True)
    ex0 = TpuShuffleManager(conf, is_driver=False, executor_id="obs-ex-0")
    ex1 = TpuShuffleManager(conf, is_driver=False, executor_id="obs-ex-1")
    shuffle_id = 7731  # unlikely to collide with other tests' bindings
    try:
        handle = BaseShuffleHandle(
            shuffle_id=shuffle_id, num_maps=2,
            partitioner=HashPartitioner(4),
        )
        driver.register_shuffle(handle)
        for map_id, ex in [(0, ex0), (1, ex1)]:
            w = ex.get_writer(handle, map_id)
            w.write(iter((f"k{i % 53}", i) for i in range(2000)))
            assert w.stop(True) is not None
        ex0.finalize_maps(shuffle_id)
        ex1.finalize_maps(shuffle_id)
        for ex, (lo, hi) in [(ex0, (0, 2)), (ex1, (2, 4))]:
            n = sum(1 for _ in ex.get_reader(handle, lo, hi).read())
            assert n > 0

        # -- satellite: manager snapshot surfaces reader-side metrics --
        snap0 = ex0.metrics_snapshot()
        sr = snap0["shuffle_read"]
        assert sr["remote_blocks"] > 0
        assert sr["local_blocks"] > 0
        assert sr["remote_bytes"] > 0
        assert sr["local_bytes"] > 0
        assert sr["records_read"] > 0

        # -- registry: counters present from every host layer ----------
        reg = get_registry().snapshot()
        counters = reg["counters"]

        def layer_total(prefix):
            return sum(v for k, v in counters.items() if k.startswith(prefix))

        assert layer_total("transport.sends") > 0
        assert layer_total("transport.recvs") > 0
        assert layer_total("rpc.messages") > 0
        assert layer_total("writer.map_outputs") > 0
        assert layer_total("writer.bytes_written") > 0
        assert layer_total("mempool.hits") + layer_total("mempool.misses") > 0
        assert layer_total("reader.remote_blocks") > 0
        # rpc handling latency histograms recorded per message type
        assert any(
            k.startswith("rpc.handle_ms") and v["count"] > 0
            for k, v in reg["histograms"].items()
        )
        # the role-filtered view the manager snapshot embeds
        role_counters = snap0["registry"]["counters"]
        assert any(k.startswith("writer.") for k in role_counters)
        assert all(
            "role=" not in k or "role=obs-ex-0" in k for k in role_counters
        )

        # -- trace: publish/resolve/fetch share one id across roles ----
        path = tmp_path / "trace.json"
        doc = export_chrome_trace(
            str(path), [driver.tracer, ex0.tracer, ex1.tracer]
        )
        on_disk = json.loads(path.read_text())
        assert on_disk == doc
        events = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        ours = [
            e for e in events
            if e["args"].get("shuffle_id") == shuffle_id
        ]
        by_phase = {}
        for e in ours:
            by_phase.setdefault(e["name"], []).append(e)
        for phase in ("shuffle.register", "shuffle.publish",
                      "shuffle.resolve", "shuffle.fetch"):
            assert by_phase.get(phase), f"no {phase} span for the shuffle"
        trace_id = driver.tracer.trace_for(shuffle_id)
        assert trace_id != 0
        want = f"{trace_id:#x}"
        correlated = [e for e in ours if e["args"].get("trace_id") == want]
        roles_sharing = {e["pid"] for e in correlated}
        assert len(roles_sharing) >= 2, (
            "trace id must correlate spans across driver and executor roles"
        )
        phases_sharing = {e["name"] for e in correlated}
        assert {"shuffle.resolve", "shuffle.fetch"} <= phases_sharing
    finally:
        ex0.stop()
        ex1.stop()
        driver.stop()


def test_metrics_snapshot_delta_between_runs():
    """delta() isolates one run's traffic from the process-global
    counters — the pattern bench artifacts use."""
    reg = get_registry()
    prev = reg.snapshot(prefix="obsdelta.")
    reg.counter("obsdelta.n").inc(3)
    d = reg.delta(prev, prefix="obsdelta.")
    assert d["counters"]["obsdelta.n"] == 3


# ---------------------------------------------------------------------------
# exchange-layer counters (jax; cpu platform)
# ---------------------------------------------------------------------------

def test_exchange_registry_counters():
    jax = pytest.importorskip("jax")
    import numpy as np
    from jax.sharding import Mesh

    from sparkrdma_tpu.ops.exchange import ExchangeProgram, pack_blocks

    prev = get_registry().snapshot(prefix="exchange.")
    mesh = Mesh(np.array(jax.devices()[:1]), ("exec",))
    prog = ExchangeProgram(mesh)
    send, counts = pack_blocks([b"abc"], 1024)
    prog.exchange(send, counts)
    d = get_registry().delta(prev, prefix="exchange.")
    assert d["counters"]["exchange.exchanges{schedule=a2a}"] == 1
    assert d["counters"]["exchange.bytes_sent{schedule=a2a}"] == 1024
    assert d["counters"]["exchange.bytes_received_valid{schedule=a2a}"] == 3
    # stats dict kept for back-compat mirrors the registry
    assert prog.stats["a2a"]["exchanges"] == 1


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def test_obs_cli_demo(tmp_path):
    trace_path = tmp_path / "cli_trace.json"
    out = subprocess.run(
        [sys.executable, "-m", "sparkrdma_tpu.obs", "--demo",
         "--trace-out", str(trace_path), "--indent", "0"],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    snap = json.loads(out.stdout)
    layers = {k.split(".")[0] for k in snap["counters"]}
    assert {"transport", "rpc", "writer", "mempool", "reader"} <= layers
    doc = json.loads(trace_path.read_text())
    names = {e["name"] for e in doc["traceEvents"] if e.get("ph") == "X"}
    assert {"shuffle.publish", "shuffle.resolve", "shuffle.fetch"} <= names
