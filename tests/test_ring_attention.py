"""Ring attention vs dense reference on the 8-device CPU mesh."""

import numpy as np
import pytest

import jax.numpy as jnp

from sparkrdma_tpu.ops.ring_attention import RingAttention, reference_attention
from sparkrdma_tpu.parallel.mesh import make_mesh


def _inputs(b=2, s=64, h=2, d=16, seed=0):
    rng = np.random.default_rng(seed)
    def mk():
        return jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))

    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_dense(causal):
    q, k, v = _inputs()
    ring = RingAttention(make_mesh())
    out = ring(q, k, v, causal=causal)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)


def test_ring_compile_once():
    q, k, v = _inputs()
    ring = RingAttention(make_mesh())
    ring(q, k, v)
    assert len(ring._cache) == 1
    ring(q, k, v)
    assert len(ring._cache) == 1
    ring(q, k, v, causal=True)
    assert len(ring._cache) == 2


def test_ring_bf16_inputs():
    q, k, v = _inputs(s=32)
    q, k, v = (x.astype(jnp.bfloat16) for x in (q, k, v))
    ring = RingAttention(make_mesh())
    out = ring(q, k, v)
    ref = reference_attention(q, k, v)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32),
        np.asarray(ref, dtype=np.float32),
        rtol=5e-2,
        atol=5e-2,
    )
