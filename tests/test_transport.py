"""Transport layer: SEND delivery, one-sided READ service, send-budget
permit arithmetic under overflow (SURVEY.md §4 property target:
RdmaChannel.java:589-625), error latching, stale-channel replacement."""

import threading
import time

import pytest

from sparkrdma_tpu.memory.buffer import TpuBuffer
from sparkrdma_tpu.transport import FnListener, TpuNode
from sparkrdma_tpu.utils.config import TpuShuffleConf


def _mk_node(executor_id, recv=None, conf=None):
    return TpuNode(
        conf or TpuShuffleConf(),
        "127.0.0.1",
        is_executor=True,
        executor_id=executor_id,
        recv_listener=recv,
    )


def test_send_delivery_and_read():
    received = []
    got = threading.Event()

    def on_recv(ch, payload):
        received.append(payload)
        got.set()

    a = _mk_node("exec-a")
    b = _mk_node("exec-b", recv=on_recv)
    try:
        ch = a.get_channel("127.0.0.1", b.port)

        # SEND: RPC segment delivery to b's recv listener
        done = threading.Event()
        ch.send_in_queue(FnListener(lambda _: done.set()), [b"hello-rpc"])
        assert done.wait(5) and got.wait(5)
        assert received == [b"hello-rpc"]

        # one-sided READ: register a region on b, pull it from a
        src = TpuBuffer(b.pd, 64 * 1024)
        src.write(bytes(range(256)) * 256)
        dst = TpuBuffer(a.pd, 64 * 1024, register=False)
        read_done = threading.Event()
        ch.read_in_queue(
            FnListener(lambda _: read_done.set()),
            [dst.view],
            [(src.mkey, 0, 64 * 1024)],
        )
        assert read_done.wait(5)
        assert dst.read() == src.read()
        src.free()
        dst.free()
    finally:
        a.stop()
        b.stop()


def test_multi_block_read_scatter():
    a = _mk_node("exec-a2")
    b = _mk_node("exec-b2")
    try:
        ch = a.get_channel("127.0.0.1", b.port)
        src = TpuBuffer(b.pd, 4096)
        src.write(b"A" * 1000 + b"B" * 2000 + b"C" * 1096)
        dst = TpuBuffer(a.pd, 4096, register=False)
        done = threading.Event()
        # three remote blocks, two destination views
        ch.read_in_queue(
            FnListener(lambda _: done.set()),
            [dst.view[:1500], dst.view[1500:4096]],
            [(src.mkey, 0, 1000), (src.mkey, 1000, 2000), (src.mkey, 3000, 1096)],
        )
        assert done.wait(5)
        assert dst.read() == src.read()
        src.free()
        dst.free()
    finally:
        a.stop()
        b.stop()


def test_read_unknown_mkey_fails_listener():
    a = _mk_node("exec-a3")
    b = _mk_node("exec-b3")
    try:
        ch = a.get_channel("127.0.0.1", b.port)
        dst = TpuBuffer(a.pd, 1024, register=False)
        failed = threading.Event()
        errors = []
        ch.read_in_queue(
            FnListener(None, lambda e: (errors.append(e), failed.set())),
            [dst.view[:100]],
            [(999, 0, 100)],
        )
        assert failed.wait(5)
        assert "not registered" in str(errors[0])
        # channel survives a failed READ (no error latch)
        assert ch.is_connected
        dst.free()
    finally:
        a.stop()
        b.stop()


def test_send_budget_overflow_drains():
    conf = TpuShuffleConf({"tpu.shuffle.sendQueueDepth": "256"})
    a = _mk_node("exec-a4", conf=conf)
    b = _mk_node("exec-b4", conf=conf)
    try:
        ch = a.get_channel("127.0.0.1", b.port)
        n = 600  # > sendQueueDepth permits in flight at once
        done = [threading.Event() for _ in range(n)]
        for i in range(n):
            ch.send_in_queue(FnListener(lambda _, ev=done[i]: ev.set()), [b"x" * 100])
        for ev in done:
            assert ev.wait(5)
        # all permits reclaimed after completions
        assert ch._send_budget == conf.send_queue_depth
        assert not ch._overflow
    finally:
        a.stop()
        b.stop()


def test_peer_loss_fails_outstanding_and_latches():
    a = _mk_node("exec-a5")
    b = _mk_node("exec-b5")
    ch = a.get_channel("127.0.0.1", b.port)
    failures = []
    failed = threading.Event()
    # stop b abruptly; subsequent posts must fail, not hang
    b.stop()
    time.sleep(0.1)
    dst = TpuBuffer(a.pd, 1024, register=False)
    ch.read_in_queue(
        FnListener(None, lambda e: (failures.append(e), failed.set())),
        [dst.view[:10]],
        [(1, 0, 10)],
    )
    assert failed.wait(5)
    assert not ch.is_connected
    dst.free()
    a.stop()


def test_channel_cache_and_stale_replacement():
    a = _mk_node("exec-a6")
    b = _mk_node("exec-b6")
    try:
        ch1 = a.get_channel("127.0.0.1", b.port)
        ch2 = a.get_channel("127.0.0.1", b.port)
        assert ch1 is ch2  # cached
        ch1.stop()
        time.sleep(0.05)
        ch3 = a.get_channel("127.0.0.1", b.port)
        assert ch3 is not ch1  # dead channel replaced
        assert ch3.is_connected
    finally:
        a.stop()
        b.stop()


def test_connect_refused_raises_after_attempts():
    conf = TpuShuffleConf({"tpu.shuffle.maxConnectionAttempts": "2"})
    a = _mk_node("exec-a7", conf=conf)
    try:
        with pytest.raises(IOError):
            a.get_channel("127.0.0.1", 1)  # nothing listens on port 1
    finally:
        a.stop()


def test_rpc_data_channel_split_python_plane():
    """Purpose-keyed channel caching + rpc round trip while the data
    channel is continuously saturated (python-plane twin of the native
    HOL test; reference channel roles RdmaChannel.java:110-154)."""
    rpc_reply = threading.Event()

    def server_recv(ch, payload):
        ch.send_in_queue(None, [b"locs:" + payload])

    def client_recv(ch, payload):
        rpc_reply.set()

    a = _mk_node("hol-srv", recv=server_recv)
    b = _mk_node("hol-cli", recv=client_recv)
    try:
        ch_data = b.get_channel("127.0.0.1", a.port, purpose="data")
        ch_rpc = b.get_channel("127.0.0.1", a.port, purpose="rpc")
        assert ch_data is not ch_rpc
        assert b.get_channel("127.0.0.1", a.port, purpose="data") is ch_data
        # peer sees two passive channels for "hol-cli": one per kind
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            with a._lock:
                kinds = sorted(k[1] for k in a._passive if k[0] == "hol-cli")
            if len(kinds) == 2:
                break
            time.sleep(0.01)
        assert kinds == [0, 1]

        from transport_harness import saturate_reads_until

        src = TpuBuffer(a.pd, 4 << 20)
        src.write(bytes(range(256)) * (4 << 12))
        read_errs = []
        drained = threading.Event()
        dst = memoryview(bytearray(4 << 20))
        finish = saturate_reads_until(
            ch_data, src.mkey, 4 << 20, [dst], rpc_reply, read_errs, drained
        )
        ch_rpc.send_in_queue(None, [b"fetch-partition-locations"])
        assert rpc_reply.wait(10.0), "rpc starved behind in-flight data READs"
        finish()
        assert drained.wait(30), read_errs
        assert not read_errs, read_errs
        src.free()

        # losing the data channel must NOT signal peer loss while the
        # rpc channel survives (peer loss is per-peer, not per-flavor)
        lost = []
        a._peer_lost_listener = lost.append
        ch_data.stop()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            with a._lock:
                left = [k[1] for k in a._passive if k[0] == "hol-cli"]
            if len(left) == 1:
                break
            time.sleep(0.01)
        assert left == [0]  # rpc flavor survives
        time.sleep(0.2)
        assert lost == []
    finally:
        a.stop()
        b.stop()
