"""End-to-end shuffle: driver hub + 2 executors over real TCP, both
writer methods, remote one-sided READs, aggregation, ordering, and
executor-loss pruning."""

import threading

import pytest

from sparkrdma_tpu.shuffle.handle import (
    Aggregator,
    BaseShuffleHandle,
    HashPartitioner,
)
from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
from sparkrdma_tpu.utils.config import TpuShuffleConf


def _cluster(method: str, extra_conf=None):
    conf = TpuShuffleConf(
        {
            "tpu.shuffle.shuffleWriteMethod": method,
            # small blocks to exercise chunking/grouping
            "tpu.shuffle.shuffleWriteBlockSize": "65536",
            "tpu.shuffle.shuffleReadBlockSize": "65536",
            **(extra_conf or {}),
        }
    )
    driver = TpuShuffleManager(conf, is_driver=True)
    ex0 = TpuShuffleManager(conf, is_driver=False, executor_id="exec-0")
    ex1 = TpuShuffleManager(conf, is_driver=False, executor_id="exec-1")
    return conf, driver, ex0, ex1


def _stop_all(*managers):
    for m in managers:
        m.stop()


def _run_shuffle(method, num_records=4000, num_partitions=5):
    conf, driver, ex0, ex1 = _cluster(method)
    try:
        handle = BaseShuffleHandle(
            shuffle_id=0, num_maps=4, partitioner=HashPartitioner(num_partitions)
        )
        driver.register_shuffle(handle)

        # 4 map tasks: 2 on each executor; records (k, v) with k spread
        def records_for(map_id):
            return [
                (f"key-{(map_id * num_records + i) % 997}", map_id * num_records + i)
                for i in range(num_records)
            ]

        expected = {}
        for map_id, ex in [(0, ex0), (1, ex0), (2, ex1), (3, ex1)]:
            for k, v in records_for(map_id):
                expected.setdefault(k, []).append(v)
            w = ex.get_writer(handle, map_id)
            w.write(iter(records_for(map_id)))
            status = w.stop(True)
            assert status is not None and status.map_id == map_id
        ex0.finalize_maps(0)
        ex1.finalize_maps(0)

        # reduce: each executor reads a slice of partitions (local + remote)
        got = {}
        for ex, (lo, hi) in [(ex0, (0, 3)), (ex1, (3, num_partitions))]:
            reader = ex.get_reader(handle, lo, hi)
            for k, v in reader.read():
                got.setdefault(k, []).append(v)
            # data crossed executors: either as remote one-sided READs
            # or as push-merged segments already landed on this side
            # (push is best-effort, so which one wins is timing-dependent)
            assert reader.metrics.remote_blocks > 0 or (
                reader.metrics.merged_blocks > 0
            )
            assert reader.metrics.local_blocks > 0

        assert set(got) == set(expected)
        for k in expected:
            assert sorted(got[k]) == sorted(expected[k]), f"mismatch for {k}"
    finally:
        _stop_all(ex0, ex1, driver)


def test_wrapper_shuffle_end_to_end():
    _run_shuffle("wrapper")


def test_chunked_agg_shuffle_end_to_end():
    _run_shuffle("chunkedpartitionagg")


def test_aggregation_and_ordering():
    conf, driver, ex0, ex1 = _cluster("wrapper")
    try:
        agg = Aggregator(
            create_combiner=lambda v: v,
            merge_value=lambda c, v: c + v,
            merge_combiners=lambda a, b: a + b,
        )
        handle = BaseShuffleHandle(
            shuffle_id=0,
            num_maps=2,
            partitioner=HashPartitioner(3),
            aggregator=agg,
            map_side_combine=True,
            key_ordering=True,
        )
        driver.register_shuffle(handle)
        data = [(f"k{i % 10}", 1) for i in range(1000)]
        for map_id, ex in [(0, ex0), (1, ex1)]:
            w = ex.get_writer(handle, map_id)
            w.write(iter(data))
            w.stop(True)
        ex0.finalize_maps(0)
        ex1.finalize_maps(0)

        out = []
        for ex, (lo, hi) in [(ex0, (0, 2)), (ex1, (2, 3))]:
            part = list(ex.get_reader(handle, lo, hi).read())
            # ordering within each reader's range
            assert part == sorted(part, key=lambda kv: kv[0])
            out.extend(part)
        assert dict(out) == {f"k{i}": 200 for i in range(10)}
    finally:
        _stop_all(ex0, ex1, driver)


def test_executor_loss_prunes_locations():
    conf, driver, ex0, ex1 = _cluster("wrapper")
    try:
        handle = BaseShuffleHandle(shuffle_id=0, num_maps=2, partitioner=HashPartitioner(2))
        driver.register_shuffle(handle)
        for map_id, ex in [(0, ex0), (1, ex1)]:
            w = ex.get_writer(handle, map_id)
            w.write(iter([(f"m{map_id}-{i}", i) for i in range(100)]))
            w.stop(True)
        # wait for publishes to land
        import time

        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            with driver._lock:
                if driver._maps_done.get(0, 0) >= 2:
                    break
            time.sleep(0.02)
        with driver._lock:
            before = sum(len(v) for v in driver._partition_locations[0].values())
        assert before > 0
        ex1.stop()  # abrupt loss → driver prunes via peer-loss event
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            with driver._lock:
                locs = [
                    loc
                    for v in driver._partition_locations[0].values()
                    for loc in v
                ]
            if all(loc.manager_id.executor_id != "exec-1" for loc in locs):
                break
            time.sleep(0.02)
        assert all(loc.manager_id.executor_id != "exec-1" for loc in locs)
    finally:
        _stop_all(ex0, driver)


def test_fetch_defers_until_maps_complete():
    """A reducer that asks early must still see all map output."""
    conf, driver, ex0, ex1 = _cluster("wrapper")
    try:
        handle = BaseShuffleHandle(shuffle_id=0, num_maps=2, partitioner=HashPartitioner(2))
        driver.register_shuffle(handle)
        w0 = ex0.get_writer(handle, 0)
        w0.write(iter([("a", 1), ("b", 2)]))
        w0.stop(True)

        results = {}

        def read_early():
            results["out"] = sorted(ex0.get_reader(handle, 0, 2).read())

        t = threading.Thread(target=read_early)
        t.start()
        import time

        time.sleep(0.3)  # reducer is now waiting on the deferred fetch
        w1 = ex1.get_writer(handle, 1)
        w1.write(iter([("c", 3)]))
        w1.stop(True)
        t.join(10)
        assert not t.is_alive()
        assert results["out"] == [("a", 1), ("b", 2), ("c", 3)]
    finally:
        _stop_all(ex0, ex1, driver)


def test_early_reader_sees_late_local_map_output():
    """Regression: a reducer that starts BEFORE a local map task on the
    same executor finishes must still receive that map's records (the
    local short-circuit must not snapshot before the barrier)."""
    conf, driver, ex0, ex1 = _cluster("wrapper")
    try:
        handle = BaseShuffleHandle(shuffle_id=0, num_maps=2, partitioner=HashPartitioner(1))
        driver.register_shuffle(handle)
        # map 0 on ex0 completes first
        w0 = ex0.get_writer(handle, 0)
        w0.write(iter([("a", 1)]))
        w0.stop(True)

        results = {}

        def read_early():
            results["out"] = sorted(ex0.get_reader(handle, 0, 1).read())

        t = threading.Thread(target=read_early)
        t.start()
        import time

        time.sleep(0.3)  # reader is deferred on the driver barrier
        # map 1 ALSO on ex0 finishes after the reader started
        w1 = ex0.get_writer(handle, 1)
        w1.write(iter([("b", 2)]))
        w1.stop(True)
        t.join(10)
        assert not t.is_alive()
        assert results["out"] == [("a", 1), ("b", 2)]
    finally:
        _stop_all(ex0, ex1, driver)


def test_peer_loss_rearms_map_output_barrier():
    """Regression: after an executor with published outputs dies, a new
    fetch must NOT be answered with a complete-looking location set —
    it defers (and the reducer times out into MetadataFetchFailedError)."""
    import time

    from sparkrdma_tpu.shuffle.errors import MetadataFetchFailedError

    conf, driver, ex0, ex1 = _cluster(
        "wrapper", {"tpu.shuffle.partitionLocationFetchTimeoutMs": "500"}
    )
    try:
        handle = BaseShuffleHandle(shuffle_id=0, num_maps=2, partitioner=HashPartitioner(2))
        driver.register_shuffle(handle)
        for map_id, ex in [(0, ex0), (1, ex1)]:
            w = ex.get_writer(handle, map_id)
            w.write(iter([(f"m{map_id}-{i}", i) for i in range(100)]))
            w.stop(True)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            with driver._lock:
                if driver._maps_done.get(0, 0) >= 2:
                    break
            time.sleep(0.02)
        ex1.stop()  # lose exec-1 and its published map output
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            with driver._lock:
                if driver._maps_done.get(0, 0) < 2:
                    break
            time.sleep(0.02)
        with driver._lock:
            assert driver._maps_done.get(0, 0) < 2  # barrier re-armed
        reader = ex0.get_reader(handle, 0, 2)
        with pytest.raises(MetadataFetchFailedError):
            list(reader.read())
    finally:
        _stop_all(ex0, driver)


def test_chunked_agg_poisoned_by_dirty_failed_map():
    """Regression: a failed map task that already flushed frames into
    the shared logs must make finalize_and_publish refuse to publish."""
    from sparkrdma_tpu.shuffle.errors import ShuffleError

    conf, driver, ex0, ex1 = _cluster(
        "chunkedpartitionagg",
        {"tpu.shuffle.shuffleWriteFlushSize": "4096"},  # flush early
    )
    try:
        handle = BaseShuffleHandle(shuffle_id=0, num_maps=2, partitioner=HashPartitioner(1))
        driver.register_shuffle(handle)
        ok = ex0.get_writer(handle, 0)
        ok.write(iter([("a", i) for i in range(50)]))
        ok.stop(True)
        bad = ex0.get_writer(handle, 1)
        bad.write(iter([("b", "x" * 256) for _ in range(100)]))  # > flush size
        bad.stop(False)  # fails after flushing frames
        with pytest.raises(ShuffleError):
            ex0.finalize_maps(0)
    finally:
        _stop_all(ex0, ex1, driver)


def test_chunked_agg_clean_failed_map_does_not_poison():
    """A failed map that never flushed leaves the logs publishable."""
    conf, driver, ex0, ex1 = _cluster("chunkedpartitionagg")
    try:
        handle = BaseShuffleHandle(shuffle_id=0, num_maps=2, partitioner=HashPartitioner(1))
        driver.register_shuffle(handle)
        ok = ex0.get_writer(handle, 0)
        ok.write(iter([("a", 1)]))
        ok.stop(True)
        bad = ex0.get_writer(handle, 1)
        bad.write(iter([("b", 2)]))  # small: stays buffered, never flushed
        bad.stop(False)
        ex0.finalize_maps(0)  # must not raise
    finally:
        _stop_all(ex0, ex1, driver)
