"""Push-based merged shuffle (shuffle/merge.py, DESIGN.md §18).

The plane is strictly best-effort behind the resolver/locations API:
map-side sealed blocks push toward their reducer's executor, complete
coverage seals ONE merged segment per partition, and the reduce
planner reads merged-else-original — never both, never neither. These
tests pin the contract at three layers: the read-planning rule, the
endpoint's dedup/budget/seal accounting, the wire extension's legacy
byte-identity, and the manager-level e2e where the reduce side's
per-partition reads collapse to one merged read each."""



from sparkrdma_tpu.locations import (
    BlockLocation,
    PartitionLocation,
    ShuffleManagerId,
)
from sparkrdma_tpu.obs import get_registry
from sparkrdma_tpu.rpc import PublishPartitionLocationsMsg, RpcMsg
from sparkrdma_tpu.shuffle import merge
from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle, HashPartitioner
from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
from sparkrdma_tpu.utils.config import TpuShuffleConf


def _loc(pid, length=64, mkey=1, executor="exec-0", cover=0):
    return PartitionLocation(
        ShuffleManagerId("host", 1234, executor),
        pid,
        BlockLocation(0, length, mkey, merged_cover=cover),
    )


def _counter_total(delta, needle):
    return sum(
        v for k, v in delta.get("counters", {}).items() if needle in k
    )


# ----------------------------------------------------------------------
# plan_reads: the merged-else-original rule
# ----------------------------------------------------------------------
def test_plan_reads_prefers_full_coverage_merged():
    origs = [_loc(0, mkey=i) for i in range(1, 4)]
    merged_loc = _loc(0, length=192, mkey=9, executor="exec-1", cover=3)
    selected, fallbacks = merge.plan_reads(origs + [merged_loc])
    assert selected == [merged_loc]
    assert fallbacks == {0: origs}


def test_plan_reads_partial_coverage_keeps_originals():
    """A merged segment covering fewer (or more) blocks than the
    partition actually published is NEVER selected — a dropped push or
    a duplicate publish silently leaves the originals authoritative."""
    origs = [_loc(0, mkey=i) for i in range(1, 4)]
    for cover in (1, 2, 4):
        stale = _loc(0, mkey=9, cover=cover)
        selected, fallbacks = merge.plan_reads(origs + [stale])
        assert selected == origs
        assert fallbacks == {}
    # merged with NO originals at all: nothing to substitute for
    alone = _loc(5, mkey=9, cover=2)
    selected, fallbacks = merge.plan_reads([alone] + origs)
    assert selected == origs
    assert fallbacks == {}


def test_plan_reads_mixed_partitions_independent():
    """Partition selection is independent: pid 0 reads merged, pid 1
    (no merged candidate) reads originals, pid 2's partial-coverage
    candidate is dropped."""
    o0 = [_loc(0, mkey=i) for i in (1, 2)]
    o1 = [_loc(1, mkey=3)]
    o2 = [_loc(2, mkey=i) for i in (4, 5)]
    m0 = _loc(0, mkey=10, cover=2)
    m2 = _loc(2, mkey=11, cover=1)  # stale
    selected, fallbacks = merge.plan_reads(o0 + o1 + o2 + [m0, m2])
    assert selected == [m0] + o1 + o2
    assert fallbacks == {0: o0}


# ----------------------------------------------------------------------
# wire: trailing merged-cover extension (marker 0xFFFD)
# ----------------------------------------------------------------------
def test_publish_msg_merged_ext_roundtrip_and_legacy_identity():
    """merged_cover rides the frame and survives parsing; frames with
    NO merged locations are byte-identical to the pre-extension layout
    (the feature-off acceptance bar)."""
    locs = [_loc(0, mkey=3), _loc(1, mkey=4)]
    merged_locs = locs + [_loc(2, length=128, mkey=9, cover=2)]
    msg = PublishPartitionLocationsMsg(7, -1, merged_locs)
    (seg,) = msg.to_segments(4096)
    parsed = RpcMsg.parse_segment(seg)
    assert [loc.block.merged_cover for loc in parsed.locations] == [0, 0, 2]

    # legacy byte-identity: cover-0-only frames carry ZERO extension bytes
    plain = PublishPartitionLocationsMsg(7, -1, locs)
    baseline = PublishPartitionLocationsMsg(
        7, -1,
        [
            PartitionLocation(
                loc.manager_id, loc.partition_id,
                BlockLocation(loc.block.address, loc.block.length, loc.block.mkey),
            )
            for loc in locs
        ],
    )
    assert plain.to_segments(4096) == baseline.to_segments(4096)


def test_publish_msg_merged_ext_survives_segmentation():
    locs = [
        _loc(i, length=32 + i, mkey=100 + i, cover=(i % 3))
        for i in range(30)
    ]
    msg = PublishPartitionLocationsMsg(9, -1, locs)
    segments = msg.to_segments(256)
    assert len(segments) > 1
    got = []
    for seg in segments:
        got.extend(RpcMsg.parse_segment(seg).locations)
    for i, loc in enumerate(sorted(got, key=lambda x: x.partition_id)):
        assert loc.block.merged_cover == i % 3


# ----------------------------------------------------------------------
# endpoint: dedup, budget, complete-coverage sealing
# ----------------------------------------------------------------------
def test_merge_endpoint_dedup_and_coverage_seal():
    reg = get_registry()
    conf = TpuShuffleConf()
    driver = TpuShuffleManager(conf, is_driver=True)
    ex = TpuShuffleManager(conf, is_driver=False, executor_id="mep-0")
    try:
        ep = ex.merge_endpoint
        assert ep is not None  # push is on by default
        before = reg.snapshot(prefix="push.")
        handle = BaseShuffleHandle(
            shuffle_id=31, num_maps=2, partitioner=HashPartitioner(1)
        )
        driver.register_shuffle(handle)
        # two sources, one pid; duplicate delivery of (src-a, 0) dedups
        ep.push_blocks(31, "src-a", [(0, 0, b"aaaa")])
        ep.push_blocks(31, "src-a", [(0, 0, b"aaaa")])  # dup
        ep.push_blocks(
            31, "src-a", [], final={"counts": {0: 1}, "committed": 1,
                                    "num_maps": 2}
        )
        # not sealed yet: src-b's marker is missing
        delta = reg.delta(before, prefix="push.")
        assert _counter_total(delta, "merge_segments") == 0
        assert _counter_total(delta, "dedup_drops") == 1
        ep.push_blocks(
            31, "src-b", [(0, 0, b"bbbb")],
            final={"counts": {0: 1}, "committed": 1, "num_maps": 2},
        )
        delta = reg.delta(before, prefix="push.")
        assert _counter_total(delta, "merge_segments") == 1
        # the sealed segment registered with the driver as a location
        # carrying merged_cover == 2, alongside nothing else (no map
        # outputs were published in this synthetic setup)
        # read the driver registry directly: a location-only merged
        # publish never advances the map-output barrier, so a real
        # fetch would (correctly) block until maps also published —
        # and the executor's publish RPC lands asynchronously
        import time as _time

        merged_locs = []
        deadline = _time.time() + 10
        while _time.time() < deadline and not merged_locs:
            locs = driver._partition_locations.get(31, {}).get(0, [])
            merged_locs = [loc for loc in locs if loc.block.merged_cover]
            if not merged_locs:
                _time.sleep(0.05)
        assert len(merged_locs) == 1
        assert merged_locs[0].block.merged_cover == 2
        assert merged_locs[0].block.length == 8
        # payload order: sources sorted naturally, then seq
        view = ex.node.pd.resolve(
            merged_locs[0].block.mkey, 0, merged_locs[0].block.length
        )
        assert bytes(view) == b"aaaabbbb"
    finally:
        ex.stop()
        driver.stop()


def test_merge_endpoint_budget_drop_falls_back():
    """A partition blowing the buffer budget is abandoned — counted,
    never sealed, and late blocks for it dedup-drop."""
    reg = get_registry()
    # 64 KiB is the knob's floor; two ~40 KB pushes blow it
    conf = TpuShuffleConf({"tpu.shuffle.push.maxBufferBytes": "65536"})
    driver = TpuShuffleManager(conf, is_driver=True)
    ex = TpuShuffleManager(conf, is_driver=False, executor_id="mep-1")
    try:
        ep = ex.merge_endpoint
        before = reg.snapshot(prefix="push.")
        handle = BaseShuffleHandle(
            shuffle_id=32, num_maps=1, partitioner=HashPartitioner(1)
        )
        driver.register_shuffle(handle)
        ep.push_blocks(32, "src-a", [(0, 0, b"x" * 40_000)])
        ep.push_blocks(32, "src-a", [(0, 1, b"y" * 40_000)])  # blows budget
        ep.push_blocks(
            32, "src-a", [],
            final={"counts": {0: 2}, "committed": 1, "num_maps": 1},
        )
        delta = reg.delta(before, prefix="push.")
        assert _counter_total(delta, "budget_drops") >= 1
        assert _counter_total(delta, "merge_segments") == 0
        locs = driver._partition_locations.get(32, {}).get(0, [])
        assert not [loc for loc in locs if loc.block.merged_cover]
    finally:
        ex.stop()
        driver.stop()


# ----------------------------------------------------------------------
# e2e: chunked-agg writer pushes, reduce reads merged segments
# ----------------------------------------------------------------------
def test_push_e2e_reduce_reads_one_merged_segment_per_partition():
    """Full manager-level shuffle with the chunked-agg writer: every
    partition seals a merged segment and the reduce side issues exactly
    R merged reads (`reader.merged_reads` == partitions read) — the
    M*R -> R sequential-read collapse, proven via metrics; output
    matches the expected aggregation exactly."""
    num_partitions = 5
    reg = get_registry()
    conf = TpuShuffleConf(
        {
            "tpu.shuffle.shuffleWriteMethod": "chunkedpartitionagg",
            "tpu.shuffle.shuffleWriteBlockSize": "65536",
            "tpu.shuffle.shuffleReadBlockSize": "65536",
        }
    )
    driver = TpuShuffleManager(conf, is_driver=True)
    ex0 = TpuShuffleManager(conf, is_driver=False, executor_id="exec-0")
    ex1 = TpuShuffleManager(conf, is_driver=False, executor_id="exec-1")
    before = reg.snapshot(prefix="push.")
    before_reads = reg.snapshot(prefix="reader.merged_reads")
    try:
        handle = BaseShuffleHandle(
            shuffle_id=0, num_maps=4,
            partitioner=HashPartitioner(num_partitions),
        )
        driver.register_shuffle(handle)

        def records_for(map_id):
            return [
                (f"key-{(map_id * 4000 + i) % 997}", map_id * 4000 + i)
                for i in range(4000)
            ]

        expected = {}
        for map_id, ex in [(0, ex0), (1, ex0), (2, ex1), (3, ex1)]:
            for k, v in records_for(map_id):
                expected.setdefault(k, []).append(v)
            w = ex.get_writer(handle, map_id)
            w.write(iter(records_for(map_id)))
            assert w.stop(True) is not None
        ex0.finalize_maps(0)
        ex1.finalize_maps(0)

        delta = reg.delta(before, prefix="push.")
        assert _counter_total(delta, "pushed_blocks") > 0
        assert _counter_total(delta, "merge_segments") == num_partitions

        got = {}
        for ex, (lo, hi) in [(ex0, (0, 3)), (ex1, (3, num_partitions))]:
            reader = ex.get_reader(handle, lo, hi)
            for k, v in reader.read():
                got.setdefault(k, []).append(v)
        assert set(got) == set(expected)
        for k in expected:
            assert sorted(got[k]) == sorted(expected[k])
        # <= R + eps sequential reads: every partition was served by
        # its ONE merged segment, none fell back
        reads = _counter_total(
            reg.delta(before_reads, prefix="reader.merged_reads"),
            "merged_reads",
        )
        assert reads == num_partitions, (
            f"expected {num_partitions} merged reads, saw {reads}"
        )
        assert _counter_total(
            reg.delta(before, prefix="push."), "fallbacks"
        ) == 0
    finally:
        ex0.stop()
        ex1.stop()
        driver.stop()


def test_push_disabled_output_identical_and_legacy_frames():
    """Feature-off run: zero push metrics move, no merged locations
    exist, and the shuffle output is exactly the push-on run's output
    (the byte-identity acceptance at the record level)."""
    def run(push_on):
        conf = TpuShuffleConf(
            {
                "tpu.shuffle.shuffleWriteMethod": "chunkedpartitionagg",
                "tpu.shuffle.push.enabled": str(push_on).lower(),
            }
        )
        driver = TpuShuffleManager(conf, is_driver=True)
        ex = TpuShuffleManager(conf, is_driver=False, executor_id="solo-0")
        try:
            handle = BaseShuffleHandle(
                shuffle_id=0, num_maps=2, partitioner=HashPartitioner(3)
            )
            driver.register_shuffle(handle)
            for map_id in range(2):
                w = ex.get_writer(handle, map_id)
                w.write(iter((f"k{i % 53}", i) for i in range(2000)))
                w.stop(True)
            ex.finalize_maps(0)
            locs = ex.fetch_remote_partition_locations(0, 0, 3).result(timeout=10)
            merged_locs = [loc for loc in locs if loc.block.merged_cover]
            if push_on:
                assert merged_locs
            else:
                assert not merged_locs
            reader = ex.get_reader(handle, 0, 3)
            return sorted(reader.read())
        finally:
            ex.stop()
            driver.stop()

    assert run(True) == run(False)
