"""Device fetch plane (DESIGN.md §17): wire extension, planner
fallbacks, and cluster byte-identity — all on the emulated
``JAX_PLATFORMS=cpu`` topology tier-1 runs on."""

import numpy as np
import pytest

from sparkrdma_tpu.locations import (
    BlockLocation,
    PartitionLocation,
    ShuffleManagerId,
)
from sparkrdma_tpu.rpc import PublishPartitionLocationsMsg, RpcMsg
from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle, HashPartitioner
from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
from sparkrdma_tpu.utils import checksum
from sparkrdma_tpu.utils.config import TpuShuffleConf


def _mk_loc(pid, length, mkey, ck=0, algo=0, coords=-1, handle=0, off=0):
    return PartitionLocation(
        ShuffleManagerId("host", 1234, f"exec-{mkey}"),
        pid,
        BlockLocation(
            0, length, mkey, checksum=ck, checksum_algo=algo,
            device_coords=coords, arena_handle=handle, arena_offset=off,
        ),
    )


# ----------------------------------------------------------------------
# wire: trailing device-location extension
# ----------------------------------------------------------------------
def test_publish_msg_device_extension_roundtrip():
    """Device coordinates ride the frame next to checksums AND the
    trace id — all three trailing extensions coexist."""
    locs = [
        _mk_loc(0, 100, 7, ck=0xDEADBEEF, algo=checksum.ALGO_CRC32,
                coords=3, handle=11, off=4096),
        _mk_loc(1, 200, 8, ck=0x12345678, algo=checksum.ALGO_CRC32),
    ]
    msg = PublishPartitionLocationsMsg(5, -1, locs, trace_id=0xABC)
    out = [RpcMsg.parse_segment(s) for s in msg.to_segments(4096)]
    got = sorted(
        (loc for m in out for loc in m.locations),
        key=lambda loc: loc.partition_id,
    )
    assert (got[0].block.device_coords, got[0].block.arena_handle,
            got[0].block.arena_offset) == (3, 11, 4096)
    assert got[0].block.has_device
    # the location WITHOUT a device copy parses with the no-device mark
    assert not got[1].block.has_device
    # the other extensions still parse alongside
    assert got[0].block.checksum == 0xDEADBEEF
    assert got[1].block.checksum == 0x12345678
    assert all(m.trace_id == 0xABC for m in out)


def test_publish_msg_without_device_is_byte_identical_legacy():
    """No device info -> no extension bytes: the frame is byte-for-byte
    the pre-extension layout (what examples/foreign_client.c parses)."""
    locs = [_mk_loc(0, 64, 3), _mk_loc(1, 64, 4)]
    msg = PublishPartitionLocationsMsg(2, -1, locs)
    baseline = PublishPartitionLocationsMsg(
        2, -1,
        [
            PartitionLocation(
                loc.manager_id, loc.partition_id,
                BlockLocation(loc.block.address, loc.block.length, loc.block.mkey),
            )
            for loc in locs
        ],
    )
    assert msg.to_segments(4096) == baseline.to_segments(4096)
    (seg,) = msg.to_segments(4096)
    m = RpcMsg.parse_segment(seg)
    assert [loc.block.arena_handle for loc in m.locations] == [0, 0]


def test_publish_msg_device_ext_survives_segmentation():
    """Device coordinates stay attached to THEIR location across
    segment splits (per-segment extension tables)."""
    locs = [
        _mk_loc(i, 10 + i, 100 + i, coords=i % 4, handle=i + 1, off=i * 64)
        for i in range(40)
    ]
    msg = PublishPartitionLocationsMsg(9, -1, locs)
    segments = msg.to_segments(256)
    assert len(segments) > 1
    got = []
    for seg in segments:
        got.extend(RpcMsg.parse_segment(seg).locations)
    assert len(got) == 40
    for i, loc in enumerate(sorted(got, key=lambda x: x.partition_id)):
        assert (loc.block.device_coords, loc.block.arena_handle,
                loc.block.arena_offset) == (i % 4, i + 1, i * 64)


# ----------------------------------------------------------------------
# planner + cluster (in-process emulated topology)
# ----------------------------------------------------------------------
BLOCK = 64 << 10  # above the 16 KiB deviceFetch.minBlockBytes default


@pytest.fixture()
def cluster():
    from sparkrdma_tpu.shuffle.device_io import DeviceShuffleIO

    # python transport: these tests assert planner/fallback counters,
    # not the native read plane
    conf = TpuShuffleConf({"tpu.shuffle.transport": "python"})
    driver = TpuShuffleManager(conf, is_driver=True)
    ex_map = TpuShuffleManager(conf, is_driver=False, executor_id="dfp-map")
    ex_red = TpuShuffleManager(conf, is_driver=False, executor_id="dfp-red")
    driver.register_shuffle(
        BaseShuffleHandle(
            shuffle_id=81, num_maps=1, partitioner=HashPartitioner(3)
        )
    )
    io_map, io_red = DeviceShuffleIO(ex_map), DeviceShuffleIO(ex_red)
    try:
        yield conf, io_map, io_red
    finally:
        io_red.stop()
        io_map.stop()
        ex_red.stop()
        ex_map.stop()
        driver.stop()


def _plane_counters(role):
    from sparkrdma_tpu.obs import get_registry

    reg = get_registry()
    return (
        reg.counter("device_fetch.plane.pulls", role=role),
        reg.counter("device_fetch.plane.fallbacks", role=role),
    )


def _publish(io_map, seed=17):
    rng = np.random.default_rng(seed)
    data = {p: rng.integers(0, 256, BLOCK + p, np.uint8) for p in range(3)}
    io_map.publish_device_blocks(81, data)
    return data


def test_device_pull_engages_and_is_byte_identical(cluster):
    """Arena-resident published blocks come back via HBM pulls (the
    plane counter moves, zero fallbacks) and the bytes match a
    host-path fetch of the same shuffle exactly."""
    conf, io_map, io_red = cluster
    data = _publish(io_map)
    pulls, fallbacks = _plane_counters("dfp-red")
    p0, f0 = pulls.value, fallbacks.value

    got_dev = io_red.fetch_device_blocks(81, 0, 3, timeout_s=30)
    dev_bytes = {
        p: bytes(got_dev[p][0].read(0, len(data[p]))) for p in range(3)
    }
    for bufs in got_dev.values():
        for b in bufs:
            b.free()
    assert pulls.value - p0 == 3, "device pulls did not engage"
    assert fallbacks.value == f0

    conf.set("tpu.shuffle.deviceFetch.enabled", "false")
    got_host = io_red.fetch_device_blocks(81, 0, 3, timeout_s=30)
    host_bytes = {
        p: bytes(got_host[p][0].read(0, len(data[p]))) for p in range(3)
    }
    for bufs in got_host.values():
        for b in bufs:
            b.free()
    assert pulls.value - p0 == 3, "disabled plane still pulled"

    for p in range(3):
        assert dev_bytes[p] == data[p].tobytes(), f"device path differs p{p}"
        assert host_bytes[p] == dev_bytes[p], f"host/device differ p{p}"


def test_planner_degrades_to_host_on_arena_spill(cluster):
    """The eviction race: every published arena copy is forced off the
    device mid-job. The fetch must complete byte-exact through the host
    triple — fallbacks counted, ZERO errors, zero pulls."""
    conf, io_map, io_red = cluster
    data = _publish(io_map)
    # force the race: all advertised slabs leave the device tier
    for abuf in io_map._arena_published[81]:
        abuf.spill_to_host()
        assert abuf.spilled
    pulls, fallbacks = _plane_counters("dfp-red")
    p0, f0 = pulls.value, fallbacks.value
    got = io_red.fetch_device_blocks(81, 0, 3, timeout_s=30)
    for p in range(3):
        assert bytes(got[p][0].read(0, len(data[p]))) == data[p].tobytes()
    for bufs in got.values():
        for b in bufs:
            b.free()
    assert pulls.value == p0, "spilled slab must not be pulled"
    assert fallbacks.value - f0 == 3, "each block counts one fallback"


def test_planner_skips_blocks_below_min_bytes(cluster):
    """Blocks under deviceFetch.minBlockBytes publish no pull-worthy
    offer the planner accepts: host path, one fallback each (the device
    ext IS present — arena staging floors at the same knob, so this
    exercises the size gate directly)."""
    conf, io_map, io_red = cluster
    conf.set("tpu.shuffle.deviceFetch.minBlockBytes", "1k")
    rng = np.random.default_rng(3)
    data = {p: rng.integers(0, 256, 2048, np.uint8) for p in range(3)}
    io_map.publish_device_blocks(81, data)
    conf.set("tpu.shuffle.deviceFetch.minBlockBytes", "16k")
    pulls, fallbacks = _plane_counters("dfp-red")
    p0, f0 = pulls.value, fallbacks.value
    got = io_red.fetch_device_blocks(81, 0, 3, timeout_s=30)
    for p in range(3):
        assert bytes(got[p][0].read(0, 2048)) == data[p].tobytes()
    for bufs in got.values():
        for b in bufs:
            b.free()
    assert pulls.value == p0
    assert fallbacks.value - f0 == 3


def test_split_phase_device_pull_byte_identity(cluster):
    """The split-phase reduce pipeline (fetch/verify/stage seams) with
    device pulls flowing through: DevicePulledBlock passes verify,
    unwraps at stage, and the staged bytes match the host path."""
    conf, io_map, io_red = cluster
    data = _publish(io_map, seed=23)
    pulls, _ = _plane_counters("dfp-red")
    p0 = pulls.value

    def run_pipeline():
        staged = {}
        got = io_red.fetch_host_blocks(81, 0, 3, timeout_s=30)
        for p, blocks in got.items():
            out = []
            for hb in blocks:
                hb = io_red.verify_host_block(hb)
                out.append(io_red.stage_host_block(hb))
            staged[p] = out
        return staged

    staged_dev = run_pipeline()
    n_pulled = pulls.value - p0
    assert n_pulled == 3, "split-phase fetch did not pull"
    dev_bytes = {
        p: bytes(staged_dev[p][0].read(0, len(data[p]))) for p in range(3)
    }
    for bufs in staged_dev.values():
        for b in bufs:
            b.free()

    conf.set("tpu.shuffle.deviceFetch.enabled", "false")
    staged_host = run_pipeline()
    for p in range(3):
        host = bytes(staged_host[p][0].read(0, len(data[p])))
        assert host == data[p].tobytes()
        assert host == dev_bytes[p], f"split-phase host/device differ p{p}"
    for bufs in staged_host.values():
        for b in bufs:
            b.free()


def test_pulled_block_release_covers_abort_drain(cluster):
    """A DevicePulledBlock abandoned before staging (abort drain) frees
    its slab — no arena leak."""
    conf, io_map, io_red = cluster
    _publish(io_map, seed=29)
    got = io_red.fetch_host_blocks(81, 0, 3, timeout_s=30)
    before = io_red.device_buffers.in_use_bytes
    assert before > 0
    for blocks in got.values():
        for hb in blocks:
            hb.release()
            hb.release()  # idempotent
    # only the publisher-side arena copies remain accounted elsewhere
    assert io_red.device_buffers.in_use_bytes == 0


def test_publish_staged_batch_one_rpc(cluster):
    """N shards' windows published in one RPC: the driver's barrier
    counts every map output and a fetch sees every block."""
    conf, io_map, io_red = cluster
    rng = np.random.default_rng(41)
    windows = []
    all_data = {}
    for shard in range(3):
        data = {
            p: rng.integers(0, 256, BLOCK, np.uint8) for p in range(3)
        }
        windows.append(io_map.stage_device_blocks(81, data))
        for p, arr in data.items():
            all_data.setdefault(p, []).append(arr)
    io_map.publish_staged_batch(81, windows, num_map_outputs_each=1)
    got = io_red.fetch_device_blocks(81, 0, 3, timeout_s=30)
    try:
        for p in range(3):
            assert len(got[p]) == 3, "batched publish dropped blocks"
            have = sorted(bytes(b.read(0, BLOCK)) for b in got[p])
            want = sorted(a.tobytes() for a in all_data[p])
            assert have == want
    finally:
        for bufs in got.values():
            for b in bufs:
                b.free()
