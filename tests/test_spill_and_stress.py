"""Memory-budget spill path + native transport stress.

Covers: chunked-agg blocks spilling to registered scratch files when
the executor in-memory budget is exhausted (reference
RdmaShufflePartitionWriter.scala:42-52) with remote reads still served
from the file-backed regions; and the native data plane under
concurrent multi-megabyte READs (exercising partial-write/EPOLLOUT and
partial-read framing paths)."""

import threading

import numpy as np
import pytest

from sparkrdma_tpu.native.transport_lib import available
from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle, HashPartitioner
from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
from sparkrdma_tpu.transport import FnListener
from sparkrdma_tpu.utils.config import TpuShuffleConf


def test_chunked_agg_spills_to_file_blocks_under_budget():
    conf = TpuShuffleConf(
        {
            "tpu.shuffle.shuffleWriteMethod": "chunkedpartitionagg",
            # budget admits ~1 block; the rest must spill to scratch files
            "tpu.shuffle.shuffleWriteMaxInMemoryStoragePerExecutor": "65536",
            "tpu.shuffle.shuffleWriteBlockSize": "65536",
            "tpu.shuffle.shuffleWriteFlushSize": "8192",
        }
    )
    driver = TpuShuffleManager(conf, is_driver=True)
    ex0 = TpuShuffleManager(conf, is_driver=False, executor_id="exec-0")
    ex1 = TpuShuffleManager(conf, is_driver=False, executor_id="exec-1")
    try:
        handle = BaseShuffleHandle(
            shuffle_id=0, num_maps=2, partitioner=HashPartitioner(2)
        )
        driver.register_shuffle(handle)
        expected = {}
        rng = np.random.default_rng(0)
        for map_id, ex in [(0, ex0), (1, ex1)]:
            # incompressible values so flushed frames stay large
            recs = [
                (int(k), rng.bytes(400))
                for k in rng.integers(0, 50, 800)
            ]
            for k, v in recs:
                expected.setdefault(k, []).append(v)
            w = ex.get_writer(handle, map_id)
            w.write(iter(recs))
            w.stop(True)
        ex0.finalize_maps(0)
        ex1.finalize_maps(0)

        # the budget must actually have forced file blocks
        from sparkrdma_tpu.shuffle.writer.blocks import FileWriterBlock
        from sparkrdma_tpu.shuffle.writer.chunked_agg import ChunkedAggShuffleData

        spilled = 0
        for ex in (ex0, ex1):
            data = ex.resolver.get_shuffle_data(0)
            assert isinstance(data, ChunkedAggShuffleData)
            for pw in data._writers.values():
                spilled += sum(
                    1 for b in pw._blocks if isinstance(b, FileWriterBlock)
                )
        assert spilled > 0, "budget never forced a file-backed block"

        got = {}
        for ex, (lo, hi) in [(ex0, (0, 1)), (ex1, (1, 2))]:
            for k, v in ex.get_reader(handle, lo, hi).read():
                got.setdefault(k, []).append(v)
        assert set(got) == set(expected)
        for k in expected:
            assert sorted(got[k]) == sorted(expected[k])
    finally:
        ex0.stop()
        ex1.stop()
        driver.stop()


@pytest.mark.skipif(not available(), reason="native transport unavailable")
def test_native_concurrent_large_reads():
    from sparkrdma_tpu.transport.native_node import NativeTpuNode

    conf = TpuShuffleConf()
    a = NativeTpuNode(conf, "127.0.0.1", False, "stress-a")
    b = NativeTpuNode(conf, "127.0.0.1", True, "stress-b")
    try:
        n = 4 * 1024 * 1024
        src = np.random.default_rng(1).integers(0, 256, n, dtype=np.uint8)
        region = memoryview(bytearray(src.tobytes()))
        mkey = a.pd.register(region)
        ch = b.get_channel("127.0.0.1", a.port)

        results = []
        events = []
        for i in range(8):
            off = i * (n // 8)
            length = n // 8
            dst = memoryview(bytearray(length))
            ev = threading.Event()
            errs = []
            ch.read_in_queue(
                FnListener(
                    lambda _, e=ev: e.set(),
                    lambda ex, e=ev, er=errs: (er.append(ex), e.set()),
                ),
                [dst],
                [(mkey, off, length)],
            )
            results.append((off, length, dst, errs))
            events.append(ev)
        for ev in events:
            assert ev.wait(20), "stress read timed out"
        for off, length, dst, errs in results:
            assert not errs, errs
            assert bytes(dst) == src[off : off + length].tobytes()
    finally:
        b.stop()
        a.stop()


@pytest.mark.skipif(not available(), reason="native transport unavailable")
def test_native_send_budget_overflow_drains():
    """More posted WRs than permits: all must still complete in order
    of eligibility, with the overflow deque draining on completions."""
    from sparkrdma_tpu.transport.native_node import NativeTpuNode

    conf = TpuShuffleConf({"tpu.shuffle.sendQueueDepth": "256"})
    got = []
    done = threading.Event()
    total = 600  # > budget

    def on_recv(ch, payload):
        got.append(payload)
        if len(got) == total:
            done.set()

    a = NativeTpuNode(conf, "127.0.0.1", False, "budget-a")
    b = NativeTpuNode(conf, "127.0.0.1", True, "budget-b", recv_listener=on_recv)
    try:
        ch = a.get_channel("127.0.0.1", b.port)
        for i in range(total):
            ch.send_in_queue(FnListener(), [b"m%06d" % i])
        assert done.wait(20), f"only {len(got)}/{total} arrived"
        assert sorted(got) == [b"m%06d" % i for i in range(total)]
        assert ch._budget <= conf.send_queue_depth
    finally:
        a.stop()
        b.stop()


def test_tiered_hbm_pool_threaded_stress(tmp_path):
    """Hammer the three-tier HBM pool from several threads: stage,
    read, climb, and free race the manager-initiated spill cascades.
    The per-buffer tier locks must keep every read byte-exact and the
    accounting must return to zero with no spill files left."""
    from sparkrdma_tpu.ops.hbm_arena import MIN_BLOCK_SIZE, DeviceBufferManager

    mgr = DeviceBufferManager(
        max_bytes=3 * MIN_BLOCK_SIZE,
        max_host_bytes=2 * MIN_BLOCK_SIZE,
        spill_dir=str(tmp_path),
    )
    errors = []
    rounds = 30

    def worker(seed):
        rng = np.random.default_rng(seed)
        try:
            for i in range(rounds):
                payload = bytes([seed]) * int(rng.integers(64, MIN_BLOCK_SIZE))
                buf = mgr.stage_bytes(payload)
                if rng.integers(2):
                    buf.ensure_device()
                got = buf.read(0, len(payload))
                if got != payload:
                    errors.append(f"thread {seed} round {i}: bytes differ")
                buf.free()
        except Exception as e:  # pragma: no cover - failure reporting
            errors.append(f"thread {seed}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=worker, args=(s,)) for s in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
    assert not errors, errors[:5]
    assert mgr.in_use_bytes == 0 and mgr.host_bytes == 0
    assert list(tmp_path.iterdir()) == [], "spill files leaked"
    mgr.stop()
