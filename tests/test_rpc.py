"""RPC segmentation round-trip — the property the reference implies but
never checks (SURVEY.md §4: RdmaRpcMsg.scala:48-64 vs 142-152)."""

from sparkrdma_tpu.locations import BlockLocation, PartitionLocation, ShuffleManagerId
from sparkrdma_tpu.rpc import (
    AnnounceManagersMsg,
    FetchPartitionLocationsMsg,
    ManagerHelloMsg,
    PublishPartitionLocationsMsg,
    RpcMsg,
)

MID = ShuffleManagerId("localhost", 43210, "exec-7")


def test_hello_roundtrip():
    msg = ManagerHelloMsg(MID)
    segs = msg.to_segments(4096)
    assert len(segs) == 1
    parsed = RpcMsg.parse_segment(segs[0])
    assert isinstance(parsed, ManagerHelloMsg)
    assert parsed.manager_id == MID
    assert parsed.manager_id.port == 43210


def test_fetch_roundtrip():
    msg = FetchPartitionLocationsMsg(MID, shuffle_id=3, start_partition=5, end_partition=9)
    parsed = RpcMsg.parse_segment(msg.to_segments(4096)[0])
    assert isinstance(parsed, FetchPartitionLocationsMsg)
    assert (parsed.shuffle_id, parsed.start_partition, parsed.end_partition) == (3, 5, 9)
    assert parsed.requester == MID


def test_publish_single_segment():
    locs = [PartitionLocation(MID, 0, BlockLocation(0, 10, 1))]
    msg = PublishPartitionLocationsMsg(7, -1, locs)
    segs = msg.to_segments(4096)
    assert len(segs) == 1
    parsed = RpcMsg.parse_segment(segs[0])
    assert parsed.is_last and parsed.shuffle_id == 7 and parsed.partition_id == -1
    assert parsed.locations == locs


def test_publish_multi_segment_accumulation():
    locs = [
        PartitionLocation(MID, i % 13, BlockLocation(i * 4096, 4096, i))
        for i in range(500)
    ]
    msg = PublishPartitionLocationsMsg(42, 3, locs)
    seg_size = 512
    segs = msg.to_segments(seg_size)
    assert len(segs) > 1
    assert all(len(s) <= seg_size for s in segs)
    acc = []
    last_seen = 0
    for s in segs:
        parsed = RpcMsg.parse_segment(s)
        assert parsed.shuffle_id == 42 and parsed.partition_id == 3
        acc.extend(parsed.locations)
        if parsed.is_last:
            last_seen += 1
    assert last_seen == 1
    assert RpcMsg.parse_segment(segs[-1]).is_last
    assert acc == locs


def test_announce_multi_segment():
    mids = [ShuffleManagerId(f"host-{i}", 1000 + i, f"exec-{i}") for i in range(100)]
    msg = AnnounceManagersMsg(mids)
    segs = msg.to_segments(256)
    assert len(segs) > 1
    acc = []
    for s in segs:
        parsed = RpcMsg.parse_segment(s)
        acc.extend(parsed.manager_ids)
    assert acc == mids
    assert RpcMsg.parse_segment(segs[-1]).is_last
    assert not RpcMsg.parse_segment(segs[0]).is_last
