"""Control-plane HA (docs/RESILIENCE.md "Control-plane HA"): the
sharded, lease-replicated metadata hub and driver-crash re-adoption.

Layers under test, smallest to largest:

- ``ShardMap`` properties — full cover and minimal movement, the two
  guarantees that make a metadata-peer death invalidate only its own
  partition ranges;
- ``LeaseTable`` units — expiry, renewal fencing, takeover epochs;
- ``ShardedMetaStore`` — stale-epoch reject + retry-ladder recovery,
  the per-shard swept-publisher fence, and ``meta:kill`` fault
  re-routing;
- end to end — the driver's metadata hub killed between the map
  barrier and the reduce fan-out, in-process AND with real worker
  subprocesses: the job must complete byte-identically by executor
  RE-ADOPTION (generation-fenced re-publish of committed map outputs
  and parked replicas), never by recompute.
"""

import collections

import pytest

from sparkrdma_tpu.engine.cluster import ClusterContext
from sparkrdma_tpu.engine.context import TpuContext
from sparkrdma_tpu.locations import (
    BlockLocation,
    PartitionLocation,
    ShuffleManagerId,
)
from sparkrdma_tpu.metastore import ShardedMetaStore
from sparkrdma_tpu.metastore.lease import LeaseTable, StaleEpochError
from sparkrdma_tpu.metastore.shardmap import ShardMap
from sparkrdma_tpu.obs import get_registry
from sparkrdma_tpu.testing import faults as _faults
from sparkrdma_tpu.utils.config import TpuShuffleConf

WORDS = ["tpu", "shuffle", "rdma", "mesh", "ici", "dcn"]


# ----------------------------------------------------------------------
# shard map properties
# ----------------------------------------------------------------------
def test_shard_map_full_cover():
    """Every (shuffle, partition) key has exactly one primary and a
    deterministic, distinct follower list; partitions in the same
    range share owners (one reduce span touches few shards)."""
    ring = ShardMap([f"meta-{i}" for i in range(5)], vnodes=8,
                    range_size=4)
    for sid in range(3):
        for pid in range(64):
            owners = ring.owners(sid, pid, replicas=2)
            assert len(owners) == 3
            assert len(set(owners)) == 3
            assert owners[0] == ring.primary(sid, pid)
            assert all(o in ring.peers for o in owners)
            assert owners == ring.owners(sid, pid, replicas=2)
    for pid in range(0, 64, 4):
        base = ring.owners(0, pid, replicas=1)
        for off in range(1, 4):
            assert ring.owners(0, pid + off, replicas=1) == base


def test_shard_map_minimal_movement():
    """Removing a peer remaps ONLY the keys that peer owned; adding a
    peer steals keys only for itself. A metadata-peer death therefore
    invalidates only its own ranges."""
    peers = [f"meta-{i}" for i in range(6)]
    ring = ShardMap(peers, vnodes=16, range_size=2)
    keys = [(sid, pid) for sid in range(4) for pid in range(40)]
    before = {k: ring.primary(*k) for k in keys}
    dead = "meta-3"
    assert dead in set(before.values()), "pick a peer that owns keys"

    shrunk = ring.without_peer(dead)
    for k in keys:
        after = shrunk.primary(*k)
        if before[k] == dead:
            assert after != dead
        else:
            assert after == before[k]

    grown = ring.with_peer("meta-99")
    stolen = 0
    for k in keys:
        p = grown.primary(*k)
        assert p == before[k] or p == "meta-99"
        stolen += p == "meta-99"
    assert stolen > 0


# ----------------------------------------------------------------------
# lease units (injected clock)
# ----------------------------------------------------------------------
def test_lease_expiry_renewal_and_takeover():
    now = [0.0]
    lt = LeaseTable(["meta-0", "meta-1"], ttl_s=5.0,
                    clock=lambda: now[0])
    assert lt.live("meta-0") and lt.epoch("meta-0") == 1

    # renewal inside the TTL extends the deadline
    now[0] = 4.0
    lt.renew("meta-0", 1)
    now[0] = 8.0
    assert lt.live("meta-0")

    # a write carrying the current epoch passes; a superseded one fences
    lt.check("meta-0", 1)
    with pytest.raises(StaleEpochError):
        lt.check("meta-0", 0)

    # expiry: past the deadline the lease is dead and renew fences
    now[0] = 14.0
    assert not lt.live("meta-0")
    with pytest.raises(StaleEpochError):
        lt.renew("meta-0", 1)

    # takeover bumps the epoch and revives; the old epoch stays fenced
    new_epoch = lt.takeover("meta-0")
    assert new_epoch == 2
    assert lt.live("meta-0")
    with pytest.raises(StaleEpochError):
        lt.check("meta-0", 1)
    lt.check("meta-0", 2)
    with pytest.raises(StaleEpochError):
        lt.renew("meta-0", 1)  # superseded epoch cannot renew


# ----------------------------------------------------------------------
# store: stale-epoch reject + retry ladder, sweep fence, meta:kill
# ----------------------------------------------------------------------
def _store(extra=None, **kw):
    conf = dict({
        "tpu.shuffle.metastore.peers": "3",
        "tpu.shuffle.metastore.vnodes": "8",
        "tpu.shuffle.metastore.rangeSize": "2",
        "tpu.shuffle.metastore.retryBackoffMs": "1",
    }, **(extra or {}))
    return ShardedMetaStore(TpuShuffleConf(conf), role="test-meta", **kw)


def _locs(exec_id, map_id, pids, mkey=100):
    mid = ShuffleManagerId("127.0.0.1", 1, exec_id)
    return [
        PartitionLocation(
            mid, pid, BlockLocation(0, 3, mkey + pid, source_map=map_id)
        )
        for pid in pids
    ]


def test_stale_generation_sweep_rejected_whole():
    """A re-adoption sweep fenced by an older takeover generation must
    be rejected at entry (counted), never merged into the new era."""
    reg = get_registry()
    store = _store()
    gen0 = store.generation
    rejects0 = reg.counter(
        "metastore.stale_epoch_rejects", role="test-meta").value

    assert store.publish(1, _locs("exec-a", 0, range(4))) == 4
    gen1 = store.wipe()
    assert gen1 > gen0
    with pytest.raises(StaleEpochError):
        store.publish(1, _locs("exec-a", 0, range(4)),
                      fence_generation=gen0)
    assert reg.counter(
        "metastore.stale_epoch_rejects", role="test-meta"
    ).value == rejects0 + 1
    assert store.resolve(1, 0) == []

    # the CURRENT generation's sweep lands
    assert store.publish(1, _locs("exec-a", 0, range(4)),
                         fence_generation=gen1) == 4
    assert len(store.resolve(1, 0)) == 1


def test_stale_epoch_apply_retries_through_ladder():
    """A shard-side epoch fence mid-publish is retried through the
    retry ladder and succeeds once the route re-resolves."""
    reg = get_registry()
    store = _store()
    rejects0 = reg.counter(
        "metastore.stale_epoch_rejects", role="test-meta").value
    orig = store._apply
    flaked = {"n": 0}

    def flaky_apply(key, locs, routed, gen):
        if flaked["n"] == 0:
            flaked["n"] += 1
            raise StaleEpochError("meta-0", 1, 2)
        return orig(key, locs, routed, gen)

    store._apply = flaky_apply
    assert store.publish(2, _locs("exec-a", 0, [0])) == 1
    assert flaked["n"] == 1
    assert reg.counter(
        "metastore.stale_epoch_rejects", role="test-meta"
    ).value == rejects0 + 1
    assert len(store.resolve(2, 0)) == 1


def test_sweep_executor_fences_per_shard():
    """The swept-publisher fence holds PER SHARD: after sweeping
    exec-a from shuffle 1, its entries are gone from every shard of
    that shuffle, later publishes from it drop silently, and exec-b
    (and exec-a's entries in OTHER shuffles) survive."""
    store = _store()
    assert store.publish(1, _locs("exec-a", 0, range(8))) == 8
    assert store.publish(1, _locs("exec-b", 1, range(8), mkey=500)) == 8
    assert store.publish(7, _locs("exec-a", 2, range(4))) == 4

    store.sweep_executor("exec-a", shuffle_id=1)
    for pid in range(8):
        owners = {loc.manager_id.executor_id
                  for loc in store.resolve(1, pid)}
        assert owners == {"exec-b"}
    # tombstoned: a straggling publish from the swept executor drops
    assert store.publish(1, _locs("exec-a", 0, range(8))) == 0
    # scoped: other shuffles keep exec-a
    assert len(store.resolve(7, 0)) == 1


def test_meta_kill_fault_reroutes_publish():
    """``meta:kill:<n>[:shard=]`` (testing/faults.py): the routed peer
    dies mid-route; the store revokes its lease, shrinks the ring, and
    the publish lands on the surviving peers — full cover holds."""
    reg = get_registry()
    kills0 = reg.counter("metastore.peer_kills", role="test-meta").value
    with _faults.installed("meta:kill:1:shard=meta-1", seed=0):
        store = _store()
        assert store.publish(3, _locs("exec-a", 0, range(16))) == 16
    assert "meta-1" not in store.live_peers()
    assert reg.counter(
        "metastore.peer_kills", role="test-meta").value == kills0 + 1
    for pid in range(16):
        locs = store.resolve(3, pid)
        assert len(locs) == 1, f"pid {pid} lost by the failover"


# ----------------------------------------------------------------------
# end to end: driver hub killed mid-job
# ----------------------------------------------------------------------
def _wordcount(ctx):
    data = [(WORDS[(i * 7) % len(WORDS)], 1) for i in range(3000)]
    rdd = ctx.parallelize(data, 6).reduce_by_key(lambda a, b: a + b)
    return sorted(rdd.collect())


def test_driver_kill_in_process_byte_identity():
    """In-process topology: the hub dies between the map barrier and
    the reduce fan-out. The job completes byte-identical to a healthy
    run and the rebuilt hub was repopulated by adoption."""
    reg = get_registry()
    with TpuContext(num_executors=2) as ctx:
        baseline = _wordcount(ctx)

    a0 = reg.counter("metastore.adoptions", role="driver").value
    conf = TpuShuffleConf({
        "tpu.shuffle.faultPlan": "driver:kill:1:stage=reduce_phase",
    })
    try:
        with TpuContext(num_executors=2, conf=conf) as ctx:
            got = _wordcount(ctx)
    finally:
        _faults.uninstall()
    assert got == baseline
    assert reg.counter("metastore.adoptions", role="driver").value > a0


# NOTE on closures: cluster task functions come from factories so
# cloudpickle serializes them BY VALUE — worker subprocesses cannot
# import this test module by name.
def _make_map(seed, n=600):
    def fn():
        for i in range(n):
            yield (WORDS[(seed * 7 + i) % len(WORDS)], 1)

    return fn


def _counts_reducer():
    def red(it):
        acc = collections.Counter()
        for k, v in it:
            acc[k] += v
        return dict(acc)

    return red


def _expected(num_maps, n=600):
    expected = collections.Counter()
    for s in range(num_maps):
        for i in range(n):
            expected[WORDS[(s * 7 + i) % len(WORDS)]] += 1
    return expected


def _merged(parts):
    merged = collections.Counter()
    for p in parts:
        merged.update(p)
    return merged


def test_driver_kill_cluster_byte_identity():
    """Real worker subprocesses: the driver's hub is wiped at the
    reduce-phase entry; every worker answers the republish sweep and
    the job finishes byte-identical with adoptions counted."""
    reg = get_registry()
    a0 = reg.counter("metastore.adoptions", role="driver").value
    conf = TpuShuffleConf({
        "tpu.shuffle.faultPlan": "driver:kill:1:stage=reduce_phase",
    })
    try:
        with ClusterContext(num_executors=3, conf=conf) as cc:
            parts = cc.run_map_reduce(
                [_make_map(s) for s in range(6)], num_partitions=6,
                reduce_fn=_counts_reducer(),
            )
    finally:
        _faults.uninstall()
    assert _merged(parts) == _expected(6)
    assert reg.counter("metastore.adoptions", role="driver").value > a0


def test_driver_kill_then_exec_kill_readopts_replicas_zero_recompute():
    """The headline chaos bar: hub wiped at reduce-phase entry, THEN
    an executor hard-killed mid-reduce. The re-adoption sweep must
    restore the parked replica lineage (0xFFFC tags) into the rebuilt
    hub, so the executor loss promotes replicas instead of recomputing
    — byte-identical result, ZERO recomputed maps."""
    reg = get_registry()
    rec0 = reg.counter("elastic.recomputed_maps", role="driver").value
    promos0 = reg.counter(
        "elastic.replica_promotions", role="driver").value
    a0 = reg.counter("metastore.adoptions", role="driver").value
    conf = TpuShuffleConf({
        "tpu.shuffle.faultPlan": (
            "driver:kill:1:stage=reduce_phase;"
            "exec:kill:1:peer=proc-exec-1,stage=reduce_task"
        ),
        "tpu.shuffle.elastic.replicas": "1",
    })
    try:
        with ClusterContext(num_executors=3, conf=conf) as cc:
            parts = cc.run_map_reduce(
                [_make_map(s) for s in range(6)], num_partitions=6,
                reduce_fn=_counts_reducer(),
            )
    finally:
        _faults.uninstall()
    assert _merged(parts) == _expected(6)
    assert reg.counter("metastore.adoptions", role="driver").value > a0
    assert reg.counter(
        "elastic.replica_promotions", role="driver").value > promos0
    assert reg.counter(
        "elastic.recomputed_maps", role="driver").value == rec0
