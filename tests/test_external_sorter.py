"""ExternalSorter: spilled-run merge ordering (the Spark ExternalSorter role)."""

import random

from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle, HashPartitioner
from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
from sparkrdma_tpu.utils.config import TpuShuffleConf
from sparkrdma_tpu.utils.external_sorter import ExternalSorter


def test_in_memory_when_under_threshold():
    s = ExternalSorter(spill_threshold=1000)
    data = [(k, k * 2) for k in random.Random(0).sample(range(500), 500)]
    out = list(s.sort(iter(data)))
    assert out == sorted(data)
    assert s.spill_count == 0


def test_spilled_runs_merge_totally_ordered():
    s = ExternalSorter(spill_threshold=100)
    rng = random.Random(1)
    data = [(rng.randrange(10_000), i) for i in range(1750)]
    out = list(s.sort(iter(data)))
    assert [k for k, _ in out] == sorted(k for k, _ in data)
    assert s.spill_count == 17  # 1750 // 100 runs spilled
    assert s.spilled_records == 1700
    # every record survived the spill/merge round trip
    assert sorted(v for _, v in out) == list(range(1750))


def test_reader_orders_via_external_sorter_with_spills():
    conf = TpuShuffleConf({"tpu.shuffle.reader.sortSpillThreshold": "1024"})
    driver = TpuShuffleManager(conf, is_driver=True)
    ex0 = TpuShuffleManager(conf, is_driver=False, executor_id="exec-0")
    try:
        handle = BaseShuffleHandle(
            shuffle_id=0, num_maps=1, partitioner=HashPartitioner(1),
            key_ordering=True,
        )
        driver.register_shuffle(handle)
        rng = random.Random(2)
        recs = [(rng.randrange(100_000), i) for i in range(5000)]
        w = ex0.get_writer(handle, 0)
        w.write(iter(recs))
        w.stop(True)
        reader = ex0.get_reader(handle, 0, 1)
        out = list(reader.read())
        assert [k for k, _ in out] == sorted(k for k, _ in recs)
        assert reader.metrics.sort_spills >= 4  # 5000 records / 1024
    finally:
        ex0.stop()
        driver.stop()
