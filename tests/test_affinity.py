"""cpuList parsing + round-robin vector allocation (RdmaNode.java:221-277)."""

from sparkrdma_tpu.utils.affinity import (
    CpuVectorAllocator,
    parse_cpu_list,
    pin_current_thread,
)


def test_parse_ranges_and_singles():
    import os

    avail = os.sched_getaffinity(0)
    cpus = parse_cpu_list("0-2,5, 7 ,bogus,")
    assert all(c in avail for c in cpus)
    assert all(c in (0, 1, 2, 5, 7) for c in cpus)


def test_empty_list_means_no_pinning():
    alloc = CpuVectorAllocator("")
    assert alloc.next_vector() is None
    assert not pin_current_thread(None)


def test_round_robin_cycles():
    alloc = CpuVectorAllocator("0", seed=1)
    got = [alloc.next_vector() for _ in range(3)]
    assert got == [0, 0, 0]  # single-cpu box: same vector reused


def test_pin_current_thread_on_valid_cpu():
    import os

    cpu = sorted(os.sched_getaffinity(0))[0]
    assert pin_current_thread(cpu)
