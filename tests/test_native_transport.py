"""Native (C++) transport: verb-level tests, full shuffle e2e, and
python<->native wire interop.

The native plane (sparkrdma_tpu/native/transport.cpp) is the libdisni
equivalent — frame parsing, passive READ service, and payload streaming
run in an epoll loop outside Python (SURVEY.md §2.2)."""

import threading

import pytest

from sparkrdma_tpu.native.transport_lib import available
from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle, HashPartitioner
from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
from sparkrdma_tpu.transport import FnListener
from sparkrdma_tpu.utils.config import TpuShuffleConf

pytestmark = pytest.mark.skipif(not available(), reason="native transport unavailable")


def _native_conf(extra=None):
    return TpuShuffleConf(
        {
            "tpu.shuffle.transport": "native",
            "tpu.shuffle.shuffleWriteBlockSize": "65536",
            "tpu.shuffle.shuffleReadBlockSize": "65536",
            **(extra or {}),
        }
    )


def test_send_read_roundtrip():
    from sparkrdma_tpu.transport.native_node import NativeTpuNode

    conf = TpuShuffleConf()
    got = []
    ev = threading.Event()
    a = NativeTpuNode(conf, "127.0.0.1", False, "a")
    b = NativeTpuNode(
        conf, "127.0.0.1", True, "b",
        recv_listener=lambda ch, p: (got.append(p), ev.set()),
    )
    try:
        ch = a.get_channel("127.0.0.1", b.port)
        done = threading.Event()
        ch.send_in_queue(FnListener(lambda _: done.set()), [b"x" * 10000])
        assert done.wait(5) and ev.wait(5)
        assert got == [b"x" * 10000]

        src = memoryview(bytearray(range(256)) * 64)
        mkey = a.pd.register(src)
        ch_ba = b.get_channel("127.0.0.1", a.port)
        dst = memoryview(bytearray(4096))
        rdone = threading.Event()
        errs = []
        ch_ba.read_in_queue(
            FnListener(lambda _: rdone.set(), errs.append),
            [dst],
            [(mkey, 1024, 4096)],
        )
        assert rdone.wait(5), errs
        assert bytes(dst) == bytes(src[1024:5120])

        # bounds violation -> remote READ error, not silent corruption
        bad = threading.Event()
        failures = []
        ch_ba.read_in_queue(
            FnListener(None, lambda e: (failures.append(e), bad.set())),
            [memoryview(bytearray(8))],
            [(mkey, len(src) - 4, 8)],
        )
        assert bad.wait(5)
        assert "READ failed" in str(failures[0])
    finally:
        a.stop()
        b.stop()


def test_shuffle_e2e_over_native_transport():
    conf = _native_conf()
    driver = TpuShuffleManager(conf, is_driver=True)
    ex0 = TpuShuffleManager(conf, is_driver=False, executor_id="exec-0")
    ex1 = TpuShuffleManager(conf, is_driver=False, executor_id="exec-1")
    try:
        from sparkrdma_tpu.transport.native_node import NativeTpuNode

        assert isinstance(driver.node, NativeTpuNode)
        handle = BaseShuffleHandle(
            shuffle_id=0, num_maps=4, partitioner=HashPartitioner(5)
        )
        driver.register_shuffle(handle)
        expected = {}
        for map_id, ex in [(0, ex0), (1, ex0), (2, ex1), (3, ex1)]:
            recs = [(f"key-{(map_id * 997 + i) % 131}", i) for i in range(2000)]
            for k, v in recs:
                expected.setdefault(k, []).append(v)
            w = ex.get_writer(handle, map_id)
            w.write(iter(recs))
            w.stop(True)
        ex0.finalize_maps(0)
        ex1.finalize_maps(0)
        got = {}
        for ex, (lo, hi) in [(ex0, (0, 3)), (ex1, (3, 5))]:
            reader = ex.get_reader(handle, lo, hi)
            for k, v in reader.read():
                got.setdefault(k, []).append(v)
            assert reader.metrics.remote_blocks > 0
        assert set(got) == set(expected)
        for k in expected:
            assert sorted(got[k]) == sorted(expected[k])
        # the record plane's remote reads rode MAPPED delivery off the
        # publishers' mmap-registered sort files (zero-copy page cache)
        f0, s0 = ex0.node.read_path_stats()
        assert f0 > 0 and s0 == 0, (f0, s0)
    finally:
        ex0.stop()
        ex1.stop()
        driver.stop()


def test_python_native_wire_interop():
    """Same wire format: a pure-Python executor shuffles against a
    native driver + native peer executor."""
    native_conf = _native_conf()
    driver = TpuShuffleManager(native_conf, is_driver=True)
    # python-transport executor inherits the negotiated driver port
    py_conf = TpuShuffleConf(
        {**native_conf.to_dict(), "tpu.shuffle.transport": "python"}
    )
    ex_native = TpuShuffleManager(native_conf, is_driver=False, executor_id="exec-n")
    ex_python = TpuShuffleManager(py_conf, is_driver=False, executor_id="exec-p")
    try:
        handle = BaseShuffleHandle(
            shuffle_id=0, num_maps=2, partitioner=HashPartitioner(2)
        )
        driver.register_shuffle(handle)
        expected = {}
        for map_id, ex in [(0, ex_native), (1, ex_python)]:
            recs = [(f"k{(map_id * 31 + i) % 17}", i) for i in range(500)]
            for k, v in recs:
                expected.setdefault(k, []).append(v)
            w = ex.get_writer(handle, map_id)
            w.write(iter(recs))
            w.stop(True)
        ex_native.finalize_maps(0)
        ex_python.finalize_maps(0)
        got = {}
        for ex, (lo, hi) in [(ex_native, (0, 1)), (ex_python, (1, 2))]:
            for k, v in ex.get_reader(handle, lo, hi).read():
                got.setdefault(k, []).append(v)
        assert set(got) == set(expected)
        for k in expected:
            assert sorted(got[k]) == sorted(expected[k])
    finally:
        ex_native.stop()
        ex_python.stop()
        driver.stop()


def test_peer_loss_detected_natively():
    conf = _native_conf()
    driver = TpuShuffleManager(conf, is_driver=True)
    ex0 = TpuShuffleManager(conf, is_driver=False, executor_id="exec-0")
    try:
        handle = BaseShuffleHandle(
            shuffle_id=0, num_maps=1, partitioner=HashPartitioner(1)
        )
        driver.register_shuffle(handle)
        w = ex0.get_writer(handle, 0)
        w.write(iter([("a", 1)]))
        w.stop(True)
        import time

        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            with driver._lock:
                if driver._maps_done.get(0, 0) >= 1:
                    break
            time.sleep(0.02)
        ex0.stop()
        deadline = time.monotonic() + 5
        pruned = False
        while time.monotonic() < deadline:
            with driver._lock:
                locs = [
                    loc
                    for v in driver._partition_locations[0].values()
                    for loc in v
                ]
            if not locs:
                pruned = True
                break
            time.sleep(0.02)
        assert pruned, "driver did not prune lost native peer"
    finally:
        driver.stop()


def test_peer_death_fails_send_and_read_listeners():
    """Regression: a dying peer must fail every outstanding WR listener
    (queued sends included) — never orphan them."""
    import time

    from sparkrdma_tpu.transport.native_node import NativeTpuNode

    conf = TpuShuffleConf()
    a = NativeTpuNode(conf, "127.0.0.1", False, "death-a")
    b = NativeTpuNode(conf, "127.0.0.1", True, "death-b")
    ch = a.get_channel("127.0.0.1", b.port)
    src = memoryview(bytes(1024))
    b.pd.register(src)
    b.stop()  # peer dies

    failures = []
    fired = threading.Event()
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        ch.send_in_queue(
            FnListener(None, lambda e: (failures.append(e), fired.set())),
            [b"late"],
        )
        if fired.wait(0.3):
            break
    assert fired.is_set(), "send listener orphaned after peer death"
    a.stop()


def test_read_bounds_wraparound_rejected():
    """Regression: addr+len overflow in the native bounds check must be
    rejected as a remote error, not served from a wild pointer."""
    from sparkrdma_tpu.transport.native_node import NativeTpuNode

    conf = TpuShuffleConf()
    a = NativeTpuNode(conf, "127.0.0.1", False, "wrap-a")
    b = NativeTpuNode(conf, "127.0.0.1", True, "wrap-b")
    try:
        src = memoryview(bytes(1024))
        mkey = a.pd.register(src)
        ch = b.get_channel("127.0.0.1", a.port)
        failures = []
        fired = threading.Event()
        ch.read_in_queue(
            FnListener(None, lambda e: (failures.append(e), fired.set())),
            [memoryview(bytearray(32))],
            [(mkey, (1 << 64) - 16, 32)],
        )
        assert fired.wait(5), "wraparound read neither failed nor completed"
        assert "READ failed" in str(failures[0]) or "resolve" in str(failures[0])
    finally:
        b.stop()
        a.stop()


def test_same_host_file_fast_path():
    """shm-backed registered buffers are served via the same-host pread
    fast path (READ_REQ2 -> READ_FILE): data must be byte-identical and
    the streamed fallback must still work for anonymous regions."""
    from sparkrdma_tpu.memory.buffer import TpuBuffer
    from sparkrdma_tpu.transport.native_node import NativeTpuNode

    conf = TpuShuffleConf()
    a = NativeTpuNode(conf, "127.0.0.1", False, "fp-srv")
    b = NativeTpuNode(conf, "127.0.0.1", True, "fp-cli")
    try:
        buf = TpuBuffer(a.pd, 1 << 20, register=True)
        assert buf._shm_path is not None, "pool buffer should be shm-backed"
        import numpy as np

        src = np.random.default_rng(7).integers(
            0, 256, size=1 << 20, dtype=np.uint8
        )
        np.frombuffer(buf.view, dtype=np.uint8)[:] = src

        ch = b.get_channel("127.0.0.1", a.port)
        dst = memoryview(bytearray(65536))
        done = threading.Event()
        errs = []
        ch.read_in_queue(
            FnListener(lambda _: done.set(), lambda e: (errs.append(e), done.set())),
            [dst],
            [(buf.mkey, 12345, 65536)],
        )
        assert done.wait(5), errs
        assert not errs, errs
        assert bytes(dst) == src[12345 : 12345 + 65536].tobytes()
        # provably served by the pread fast path, not streamed (the
        # mutable-slab identity must keep the fast path alive even
        # though the slab was written AFTER registration)
        assert b.read_path_stats() == (1, 0)

        # multi-block read spanning file-backed + file-backed
        dst2 = [memoryview(bytearray(1000)), memoryview(bytearray(2000))]
        done2 = threading.Event()
        ch.read_in_queue(
            FnListener(lambda _: done2.set(), lambda e: (errs.append(e), done2.set())),
            dst2,
            [(buf.mkey, 0, 1000), (buf.mkey, 500000, 2000)],
        )
        assert done2.wait(5), errs
        assert not errs, errs
        assert bytes(dst2[0]) == src[:1000].tobytes()
        assert bytes(dst2[1]) == src[500000:502000].tobytes()

        # anonymous region on the same channel: server must fall back to
        # streaming (mixed region kinds never corrupt)
        anon = memoryview(bytes(range(256)) * 16)
        mkey2 = a.pd.register(anon)
        dst3 = memoryview(bytearray(4096))
        done3 = threading.Event()
        ch.read_in_queue(
            FnListener(lambda _: done3.set(), lambda e: (errs.append(e), done3.set())),
            [dst3],
            [(mkey2, 0, 4096)],
        )
        assert done3.wait(5), errs
        assert not errs, errs
        assert bytes(dst3) == bytes(anon)
        # 3 fast-path completions: the single READ plus one per block of
        # the aligned multi-block read (posted as one request per block)
        file_reads, streamed_reads = b.read_path_stats()
        assert file_reads == 3 and streamed_reads == 1

        # freed buffer -> unlinked file + dereg -> late READ errors out
        buf.free()
        failures = []
        fired = threading.Event()
        ch.read_in_queue(
            FnListener(None, lambda e: (failures.append(e), fired.set())),
            [memoryview(bytearray(16))],
            [(buf.mkey, 0, 16)],
        )
        assert fired.wait(5), "read of freed region neither failed nor completed"
    finally:
        b.stop()
        a.stop()


def test_mapped_file_served_via_file_fast_path(tmp_path):
    """A registered mapped shuffle file advertises its real path; a
    same-host native peer preads it from page cache."""
    from sparkrdma_tpu.memory.mapped_file import MappedFile
    from sparkrdma_tpu.transport.native_node import NativeTpuNode

    conf = TpuShuffleConf()
    a = NativeTpuNode(conf, "127.0.0.1", False, "mf-srv")
    b = NativeTpuNode(conf, "127.0.0.1", True, "mf-cli")
    try:
        import numpy as np

        data = np.random.default_rng(11).integers(
            0, 256, size=200_000, dtype=np.uint8
        ).tobytes()
        path = tmp_path / "shuffle.data"
        path.write_bytes(data)
        mf = MappedFile(str(path), a.pd, block_size=65536, partition_lengths=[120_000, 80_000])

        ch = b.get_channel("127.0.0.1", a.port)
        loc = mf.get_partition_location(1)
        dst = memoryview(bytearray(loc.length))
        done = threading.Event()
        errs = []
        ch.read_in_queue(
            FnListener(lambda _: done.set(), lambda e: (errs.append(e), done.set())),
            [dst],
            [(loc.mkey, loc.address, loc.length)],
        )
        assert done.wait(5), errs
        assert not errs, errs
        assert bytes(dst) == data[120_000:200_000]
        mf.dispose()
    finally:
        b.stop()
        a.stop()


def test_rpc_data_channel_split_no_hol_blocking():
    """RPC vs DATA channel flavors (RdmaChannel.java:110-154): a small
    control round-trip completes while the data channel is continuously
    saturated with in-flight READs, because they ride separate
    connections. READs are re-posted until the reply lands, so the data
    plane is provably busy for the whole RPC round trip."""
    from sparkrdma_tpu.transport.native_node import NativeTpuNode

    conf = TpuShuffleConf()
    rpc_reply = threading.Event()

    def server_recv(ch, payload):
        # echo back: the location-fetch request/response analogue
        ch.send_in_queue(None, [b"locs:" + payload])

    def client_recv(ch, payload):
        rpc_reply.set()

    a = NativeTpuNode(conf, "127.0.0.1", False, "hol-srv", recv_listener=server_recv)
    b = NativeTpuNode(conf, "127.0.0.1", True, "hol-cli", recv_listener=client_recv)
    try:
        ch_data = b.get_channel("127.0.0.1", a.port, purpose="data")
        ch_rpc = b.get_channel("127.0.0.1", a.port, purpose="rpc")
        # distinct connections per purpose (cached separately)
        assert ch_data is not ch_rpc
        assert ch_data.channel_id != ch_rpc.channel_id
        assert b.get_channel("127.0.0.1", a.port, purpose="data") is ch_data

        # 8 MiB registered region, streamed (no file hint -> no pread
        # fast path); 4 READ slots that repost on completion so the
        # data channel never idles until the rpc reply is observed
        from transport_harness import saturate_reads_until

        src = memoryview(bytearray(8 << 20))
        src[: 1 << 16] = bytes(range(256)) * 256
        mkey = a.pd.register(src)
        read_errs = []
        drained = threading.Event()
        dsts = [memoryview(bytearray(8 << 20)) for _ in range(4)]
        finish = saturate_reads_until(
            ch_data, mkey, 8 << 20, dsts, rpc_reply, read_errs, drained
        )
        # location-fetch round trip on the rpc channel while READs
        # saturate the data channel: must complete promptly, not once
        # the data stream goes idle
        ch_rpc.send_in_queue(None, [b"fetch-partition-locations"])
        assert rpc_reply.wait(10.0), "rpc starved behind in-flight data READs"
        finish()
        assert drained.wait(30), read_errs
        assert not read_errs, read_errs
        assert bytes(dsts[0][: 1 << 16]) == bytes(src[: 1 << 16])
    finally:
        b.stop()
        a.stop()


def test_file_fast_path_rejects_recreated_file(tmp_path):
    """A shuffle file unlinked and rewritten at the same path (task
    re-attempt) between registration and the client's pread must NOT
    serve the new file's bytes: the READ_FILE answer carries the
    registration-time (st_dev, st_ino) and the client falls back to
    streaming on mismatch, still yielding the registered bytes."""
    import os

    from sparkrdma_tpu.transport.native_node import NativeTpuNode

    conf = TpuShuffleConf()
    a = NativeTpuNode(conf, "127.0.0.1", False, "inode-srv")
    b = NativeTpuNode(conf, "127.0.0.1", True, "inode-cli")
    try:
        old = bytes([i % 251 for i in range(200_000)])
        path = tmp_path / "attempt0.data"
        path.write_bytes(old)
        # region memory holds the ORIGINAL bytes (mmap analogue: the
        # registered view outlives the directory entry)
        src = memoryview(bytearray(old))
        mkey = a.pd.register(src, file_path=str(path), file_offset=0)

        # task re-attempt rewrites the same path with different bytes
        os.unlink(path)
        path.write_bytes(bytes([(i * 7 + 3) % 251 for i in range(200_000)]))

        ch = b.get_channel("127.0.0.1", a.port, purpose="data")
        dst = memoryview(bytearray(200_000))
        done = threading.Event()
        errs = []
        ch.read_in_queue(
            FnListener(lambda _: done.set(), lambda e: (errs.append(e), done.set())),
            [dst],
            [(mkey, 0, 200_000)],
        )
        assert done.wait(10), "read never completed"
        assert not errs, errs
        assert bytes(dst) == old, (
            "recreated file at the registered path leaked its bytes into "
            "a READ of the original region"
        )
        # the identity mismatch must have forced the streamed fallback
        file_reads, streamed_reads = b.read_path_stats()
        assert file_reads == 0 and streamed_reads == 1
    finally:
        b.stop()
        a.stop()


def test_mapped_read_zero_copy_and_fallback():
    """srt_post_read_mapped delivers same-host file-backed blocks as
    zero-copy page-cache mappings and unbacked regions as one copied
    blob; bytes byte-exact either way, release() idempotent."""
    import numpy as np

    from sparkrdma_tpu.memory.buffer import TpuBuffer
    from sparkrdma_tpu.transport.native_node import NativeTpuNode

    conf = TpuShuffleConf()
    srv = NativeTpuNode(conf, "127.0.0.1", False, "map-srv")
    cli = NativeTpuNode(conf, "127.0.0.1", True, "map-cli")
    try:
        rng = np.random.default_rng(11)
        buf = TpuBuffer(srv.pd, 300_000, register=True)  # shm-backed
        src = rng.integers(0, 256, 300_000, np.uint8)
        np.frombuffer(buf.view, np.uint8)[:] = src
        ch = cli.get_channel("127.0.0.1", srv.port, purpose="data")

        def mapped_read(blocks):
            box, ev = {}, threading.Event()
            ch.read_mapped_in_queue(
                FnListener(
                    lambda d: (box.update(d=d), ev.set()),
                    lambda e: (box.update(e=e), ev.set()),
                ),
                blocks,
            )
            assert ev.wait(10), "mapped read timed out"
            assert "e" not in box, box.get("e")
            return box["d"]

        # same-host, file-backed, odd offset -> zero-copy mmap
        d = mapped_read([(buf.mkey, 1003, 50_000)])
        assert d.mapped, "expected the mmap path"
        assert bytes(d.views[0]) == src[1003:51_003].tobytes()
        d.release()
        d.release()  # idempotent
        assert cli.read_path_stats()[0] == 1  # counted as fast-path read

        # unbacked region, two blocks -> streamed fallback blob
        anon = rng.integers(0, 256, 100_000, np.uint8)
        mk2 = srv.pd.register(memoryview(anon.data))
        d2 = mapped_read([(mk2, 5, 60_000), (mk2, 70_000, 20_000)])
        assert not d2.mapped
        assert bytes(d2.views[0]) == anon[5:60_005].tobytes()
        assert bytes(d2.views[1]) == anon[70_000:90_000].tobytes()
        d2.release()
        assert cli.read_path_stats()[1] == 1  # streamed fallback counted
    finally:
        cli.stop()
        srv.stop()


def test_streamed_read_of_file_backed_region_uses_sendfile_path():
    """fileFastPath=false forces the streamed plane even for file-backed
    regions; the server then serves them via sendfile (kernel zero-copy)
    with the pinned-memory path as silent fallback — either way the
    bytes must be exact and the read counted as streamed. Loopback
    peers normally skip sendfile (measured slower without a DMA NIC);
    forceSendfile exercises the mechanism itself."""
    import numpy as np

    from sparkrdma_tpu.memory.buffer import TpuBuffer
    from sparkrdma_tpu.transport.native_node import NativeTpuNode

    srv = NativeTpuNode(
        TpuShuffleConf({"tpu.shuffle.forceSendfile": "true"}),
        "127.0.0.1", False, "sf-srv",
    )
    cli = NativeTpuNode(
        TpuShuffleConf({"tpu.shuffle.fileFastPath": "false"}),
        "127.0.0.1", True, "sf-cli",
    )
    try:
        rng = np.random.default_rng(13)
        buf = TpuBuffer(srv.pd, 1 << 20, register=True)
        src = rng.integers(0, 256, 1 << 20, np.uint8)
        np.frombuffer(buf.view, np.uint8)[:] = src
        ch = cli.get_channel("127.0.0.1", srv.port, purpose="data")
        dst = memoryview(bytearray(500_000))
        done, errs = threading.Event(), []
        ch.read_in_queue(
            FnListener(lambda _: done.set(), lambda e: (errs.append(e), done.set())),
            [dst],
            [(buf.mkey, 7777, 500_000)],
        )
        assert done.wait(10) and not errs, errs
        assert bytes(dst) == src[7777 : 7777 + 500_000].tobytes()
        f, s = cli.read_path_stats()
        assert f == 0 and s == 1, (f, s)
    finally:
        cli.stop()
        srv.stop()


def test_device_fetch_uses_mapped_delivery_cross_process():
    """fetch_device_blocks on the native transport stages straight from
    mapped page-cache windows (no pooled destination buffer): the fetch
    must be byte-exact and counted as fast-path reads."""
    import numpy as np

    from sparkrdma_tpu.shuffle.device_io import DeviceShuffleIO

    # device plane off: same-process arenas are mesh-visible, so HBM
    # pulls would short-circuit the mapped-delivery path under test
    conf = _native_conf({"tpu.shuffle.deviceFetch.enabled": "false"})
    driver = TpuShuffleManager(conf, is_driver=True)
    ex0 = TpuShuffleManager(conf, is_driver=False, executor_id="map-0")
    ex1 = TpuShuffleManager(conf, is_driver=False, executor_id="map-1")
    driver.register_shuffle(
        BaseShuffleHandle(shuffle_id=61, num_maps=1, partitioner=HashPartitioner(3))
    )
    io0, io1 = DeviceShuffleIO(ex0), DeviceShuffleIO(ex1)
    rng = np.random.default_rng(5)
    data = {p: rng.integers(0, 256, 40_000 + p * 1000, np.uint8) for p in range(3)}
    try:
        io1.publish_device_blocks(61, data)
        got = io0.fetch_device_blocks(61, 0, 3, timeout_s=30)
        for p in range(3):
            assert bytes(got[p][0].read(0, len(data[p]))) == data[p].tobytes()
        f, s = ex0.node.read_path_stats()
        assert f == 3 and s == 0, (f, s)
        for bufs in got.values():
            for b in bufs:
                b.free()
    finally:
        io0.stop()
        io1.stop()
        ex0.stop()
        ex1.stop()
        driver.stop()


def test_mapped_fetch_under_hbm_pressure_spills_and_survives():
    """Mapped delivery + tight HBM budget: staged slabs spill to the
    host tier DURING a mapped fetch; bytes stay exact from any tier
    and the budget never exceeds the cap (the tiered-store guarantees
    must hold regardless of delivery mechanism)."""
    import numpy as np

    from sparkrdma_tpu.shuffle.device_io import DeviceShuffleIO

    conf = _native_conf({"tpu.shuffle.hbm.maxBytes": str(64 * 1024)})
    driver = TpuShuffleManager(conf, is_driver=True)
    ex0 = TpuShuffleManager(conf, is_driver=False, executor_id="mp-0")
    ex1 = TpuShuffleManager(conf, is_driver=False, executor_id="mp-1")
    parts = 6
    driver.register_shuffle(
        BaseShuffleHandle(
            shuffle_id=71, num_maps=1, partitioner=HashPartitioner(parts)
        )
    )
    io0, io1 = DeviceShuffleIO(ex0), DeviceShuffleIO(ex1)
    rng = np.random.default_rng(7)
    data = {
        p: rng.integers(0, 256, 16 * 1024 - 64, np.uint8) for p in range(parts)
    }
    try:
        io1.publish_device_blocks(71, data)
        held = io0.fetch_device_blocks(71, 0, parts, timeout_s=60)
        pool = io0.device_buffers
        assert pool.spill_count > 0, "tight cap never spilled"
        assert pool.in_use_bytes <= 64 * 1024
        # every mapped-fetched block byte-exact, whichever tier holds it
        for p in range(parts):
            got = held[p][0].read(0, len(data[p]))
            assert got == data[p].tobytes(), f"partition {p} differs"
        # and the reads took the mapped fast path
        f, s = ex0.node.read_path_stats()
        assert f == parts and s == 0
        for bufs in held.values():
            for b in bufs:
                b.free()
        assert pool.in_use_bytes == 0
    finally:
        io0.stop()
        io1.stop()
        ex0.stop()
        ex1.stop()
        driver.stop()


def test_multiblock_file_read_splits_across_workers():
    """A single READ naming several file-backed blocks fans its preads
    over the worker pool (the WR-list striping analogue): one combined
    destination, one completion, bytes exact, counted as ONE fast-path
    read."""
    import numpy as np

    from sparkrdma_tpu.memory.buffer import TpuBuffer
    from sparkrdma_tpu.transport.native_node import NativeTpuNode

    srv = NativeTpuNode(TpuShuffleConf(), "127.0.0.1", False, "split-srv")
    cli = NativeTpuNode(
        TpuShuffleConf({"tpu.shuffle.fileWorkers": "4"}),
        "127.0.0.1", True, "split-cli",
    )
    try:
        rng = np.random.default_rng(23)
        buf = TpuBuffer(srv.pd, 16 << 20, register=True)
        src = rng.integers(0, 256, 16 << 20, np.uint8)
        np.frombuffer(buf.view, np.uint8)[:] = src
        ch = cli.get_channel("127.0.0.1", srv.port, purpose="data")
        # one dst covering three discontiguous blocks totalling > 4 MiB
        # (the split floor) -> the scatter path posts ONE multi-block
        # read -> one byte-balanced split file task
        blocks = [(buf.mkey, 0, 3 << 20), (buf.mkey, 4 << 20, 5 << 20),
                  (buf.mkey, 10 << 20, 2 << 20)]
        total = sum(b[2] for b in blocks)
        dst = memoryview(bytearray(total))
        done, errs = threading.Event(), []
        ch.read_in_queue(
            FnListener(lambda _: done.set(), lambda e: (errs.append(e), done.set())),
            [dst],
            blocks,
        )
        assert done.wait(10) and not errs, errs
        want = b"".join(src[a:a+l].tobytes() for _mk, a, l in blocks)
        assert bytes(dst) == want, "split multi-block read bytes differ"
        f, s = cli.read_path_stats()
        assert f == 1 and s == 0, (f, s)
        # the split actually engaged (not just the whole-task path)
        assert cli.split_parts() >= 2, cli.split_parts()
    finally:
        cli.stop()
        srv.stop()


def test_single_block_pread_stripes_across_workers():
    """ONE fat block (the common single-partition fetch) is expanded
    into contiguous sub-ranges so its pread spreads over file_workers
    threads instead of riding one: bytes exact, one fast-path read,
    stripe counter engaged."""
    import numpy as np

    from sparkrdma_tpu.memory.buffer import TpuBuffer
    from sparkrdma_tpu.transport.native_node import NativeTpuNode

    srv = NativeTpuNode(TpuShuffleConf(), "127.0.0.1", False, "stripe-srv")
    cli = NativeTpuNode(
        TpuShuffleConf({"tpu.shuffle.fileWorkers": "4"}),
        "127.0.0.1", True, "stripe-cli",
    )
    try:
        rng = np.random.default_rng(29)
        buf = TpuBuffer(srv.pd, 8 << 20, register=True)
        src = rng.integers(0, 256, 8 << 20, np.uint8)
        np.frombuffer(buf.view, np.uint8)[:] = src
        ch = cli.get_channel("127.0.0.1", srv.port, purpose="data")
        # one 8 MiB block: above the 4 MiB stripe floor, enough for
        # >= 2 sub-ranges of >= 1 MiB each across 4 workers
        dst = memoryview(bytearray(8 << 20))
        done, errs = threading.Event(), []
        ch.read_in_queue(
            FnListener(lambda _: done.set(), lambda e: (errs.append(e), done.set())),
            [dst],
            [(buf.mkey, 0, 8 << 20)],
        )
        assert done.wait(10) and not errs, errs
        assert bytes(dst) == src.tobytes(), "striped single-block bytes differ"
        f, s = cli.read_path_stats()
        assert f == 1 and s == 0, (f, s)
        assert cli.block_stripes() >= 2, cli.block_stripes()
        # the byte-balanced split then fans the sub-ranges out as parts
        assert cli.split_parts() >= 2, cli.split_parts()
    finally:
        cli.stop()
        srv.stop()

def _read_into(ch, mkey, off, length, timeout=15):
    dst = memoryview(bytearray(length))
    done, errs = threading.Event(), []
    ch.read_in_queue(
        FnListener(lambda _: done.set(), lambda e: (errs.append(e), done.set())),
        [dst],
        [(mkey, off, length)],
    )
    assert done.wait(timeout), "read timed out"
    assert not errs, errs
    return dst


def test_read_backend_byte_identity_across_backends():
    """Acceptance gate for the submission plane (DESIGN.md §24): every
    backend — auto, iouring, pread, mapped-copy — returns byte-identical
    data for the same read set, including a striped >4 MiB block and an
    unaligned offset; where io_uring is absent the iouring request
    degrades to pread with the SAME bytes."""
    import numpy as np

    from sparkrdma_tpu.memory.buffer import TpuBuffer
    from sparkrdma_tpu.transport.native_node import NativeTpuNode

    srv = NativeTpuNode(TpuShuffleConf(), "127.0.0.1", False, "bk-srv")
    cli = NativeTpuNode(TpuShuffleConf(), "127.0.0.1", True, "bk-cli")
    try:
        rng = np.random.default_rng(31)
        size = 6 << 20
        buf = TpuBuffer(srv.pd, size, register=True)
        src = rng.integers(0, 256, size, np.uint8)
        np.frombuffer(buf.view, np.uint8)[:] = src
        ch = cli.get_channel("127.0.0.1", srv.port, purpose="data")
        blocks = [(1003, 50_000), (0, 5 << 20), (5 << 20, 1 << 20)]
        n_reads = 0
        for backend in ("auto", "iouring", "pread", "mapped"):
            cli.set_read_backend(backend)
            for off, ln in blocks:
                got = _read_into(ch, buf.mkey, off, ln)
                assert bytes(got) == src[off:off + ln].tobytes(), backend
                n_reads += 1
        stats = cli.sq_stats()
        # every read went through the plane: one submit+completion per
        # resolved run, at least one run per read, batches counted
        assert stats["completions"] >= n_reads, stats
        assert stats["submits"] >= stats["completions"], stats
        assert stats["batches"] >= 1, stats
        f, s = cli.read_path_stats()
        assert f == n_reads and s == 0, (f, s)
        buf.free()
    finally:
        cli.stop()
        srv.stop()


def test_iouring_forced_enosys_falls_back_counted():
    """force_uring_probe_fail makes the availability probe behave like
    an ENOSYS kernel: reads degrade to pread byte-identically,
    transport.sq.backend_fallbacks ticks exactly once for the latch,
    and clearing the seam un-latches auto-detection."""
    import numpy as np

    from sparkrdma_tpu.memory.buffer import TpuBuffer
    from sparkrdma_tpu.transport.native_node import NativeTpuNode

    srv = NativeTpuNode(TpuShuffleConf(), "127.0.0.1", False, "en-srv")
    cli = NativeTpuNode(TpuShuffleConf(), "127.0.0.1", True, "en-cli")
    try:
        rng = np.random.default_rng(37)
        buf = TpuBuffer(srv.pd, 1 << 20, register=True)
        src = rng.integers(0, 256, 1 << 20, np.uint8)
        np.frombuffer(buf.view, np.uint8)[:] = src
        ch = cli.get_channel("127.0.0.1", srv.port, purpose="data")

        cli.force_uring_probe_fail(True)
        # first probe (sq_stats resolves the effective backend) latches
        # the forced-ENOSYS state and counts the fallback once
        assert cli.sq_stats()["backend"] == "pread"
        assert cli.sq_stats()["backend_fallbacks"] == 1
        got = _read_into(ch, buf.mkey, 12345, 500_000)
        assert bytes(got) == src[12345:512_345].tobytes()
        # the latch counts ONCE, not per read
        assert cli.sq_stats()["backend_fallbacks"] == 1

        cli.force_uring_probe_fail(False)
        stats = cli.sq_stats()
        if stats["uring_compiled"] and stats["backend"] == "iouring":
            # real kernel support: auto-detection recovered and the
            # uring plane serves the same bytes
            got2 = _read_into(ch, buf.mkey, 12345, 500_000)
            assert bytes(got2) == src[12345:512_345].tobytes()
        buf.free()
    finally:
        cli.stop()
        srv.stop()


def test_read_enosys_fault_seam():
    """The ``read:enosys`` fault kind (testing/faults.py) drives the
    same degradation through the fault grammar: the probe latches
    unavailable, the read itself proceeds and the bytes are untouched."""
    import numpy as np

    from sparkrdma_tpu.memory.buffer import TpuBuffer
    from sparkrdma_tpu.testing import faults
    from sparkrdma_tpu.transport.native_node import NativeTpuNode

    srv = NativeTpuNode(TpuShuffleConf(), "127.0.0.1", False, "fe-srv")
    cli = NativeTpuNode(TpuShuffleConf(), "127.0.0.1", True, "fe-cli")
    try:
        rng = np.random.default_rng(41)
        buf = TpuBuffer(srv.pd, 1 << 20, register=True)
        src = rng.integers(0, 256, 1 << 20, np.uint8)
        np.frombuffer(buf.view, np.uint8)[:] = src
        ch = cli.get_channel("127.0.0.1", srv.port, purpose="data")
        with faults.installed("read:enosys:1") as plan:
            got = _read_into(ch, buf.mkey, 777, 300_000)
            assert bytes(got) == src[777:300_777].tobytes()
            assert plan.injected_count("read", "enosys") == 1
        stats = cli.sq_stats()
        assert stats["backend"] == "pread", stats
        assert stats["backend_fallbacks"] >= 1, stats
        # the plan is spent: later reads are untouched and identical
        got2 = _read_into(ch, buf.mkey, 0, 1 << 20)
        assert bytes(got2) == src.tobytes()
        buf.free()
    finally:
        cli.stop()
        srv.stop()


def test_consume_sharded_lanes_bytes_and_errors():
    """consumeWorkers > 1 shards READ_DONE completion work across lane
    threads (DESIGN.md §24): bytes stay identical, completions for one
    channel keep arriving (buffer and mapped flavors both), failure
    completions still surface after peer death, and stop() drains the
    lanes without orphaning listeners."""
    import numpy as np

    from sparkrdma_tpu.memory.buffer import TpuBuffer
    from sparkrdma_tpu.transport.native_node import NativeTpuNode

    srv = NativeTpuNode(TpuShuffleConf(), "127.0.0.1", False, "cw-srv")
    cli = NativeTpuNode(
        TpuShuffleConf({"tpu.shuffle.native.consumeWorkers": "3"}),
        "127.0.0.1", True, "cw-cli",
    )
    try:
        assert cli.sq_stats()["consume_workers"] == 3
        rng = np.random.default_rng(43)
        size = 4 << 20
        buf = TpuBuffer(srv.pd, size, register=True)
        src = rng.integers(0, 256, size, np.uint8)
        np.frombuffer(buf.view, np.uint8)[:] = src
        chans = [
            cli.get_channel("127.0.0.1", srv.port, purpose=f"data-{j}")
            for j in range(3)
        ]
        # many outstanding reads spread over the lanes; record the
        # thread each completion ran on to prove the lanes engaged
        n = 24
        block = size // n
        dsts = [memoryview(bytearray(block)) for _ in range(n)]
        evs, errs, lane_threads = [], [], set()
        for i in range(n):
            ev = threading.Event()

            def ok(_, ev=ev):
                lane_threads.add(threading.current_thread().name)
                ev.set()

            def fail(e, ev=ev):
                errs.append(e)
                ev.set()

            chans[i % 3].read_in_queue(
                FnListener(ok, fail),
                [dsts[i]], [(buf.mkey, i * block, block)],
            )
            evs.append(ev)
        for ev in evs:
            assert ev.wait(15), "sharded-consume read timed out"
        assert not errs, errs
        for i in range(n):
            assert bytes(dsts[i]) == src[i * block:(i + 1) * block].tobytes()
        assert any(t.startswith("srt-consume-") for t in lane_threads), (
            "no completion ran on a consume lane", lane_threads)

        # mapped delivery rides the same lanes
        box, mev = {}, threading.Event()
        chans[0].read_mapped_in_queue(
            FnListener(lambda d: (box.update(d=d), mev.set()),
                       lambda e: (box.update(e=e), mev.set())),
            [(buf.mkey, 1003, 100_000)],
        )
        assert mev.wait(15) and "e" not in box, box.get("e")
        assert bytes(box["d"].views[0]) == src[1003:101_003].tobytes()
        box["d"].release()

        # failure completions still surface through the sharded plane
        import time

        srv.stop()
        fired = threading.Event()
        failures = []
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not fired.is_set():
            chans[1].read_in_queue(
                FnListener(None, lambda e: (failures.append(e), fired.set())),
                [memoryview(bytearray(16))],
                [(buf.mkey, 0, 16)],
            )
            fired.wait(0.3)
        assert fired.is_set(), "failure listener orphaned under sharded consume"
    finally:
        cli.stop()
        srv.stop()
