"""CI coverage for the exchange study (benchmarks/exchange_study.py) —
the artifact generator behind EXCHANGE_r05.json. The single-process
sweep runs in-process on the conftest 8-device farm; the 2-process
jax.distributed child runs for real over loopback gloo, exercising the
multi-host construction (process-local shards, non-addressable receive
accounting) that no single-process test can reach."""

import importlib.util
import json
import os

_spec = importlib.util.spec_from_file_location(
    "exchange_study",
    os.path.join(os.path.dirname(__file__), "..", "benchmarks", "exchange_study.py"),
)
exchange_study = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(exchange_study)

ROOT = os.path.join(os.path.dirname(__file__), "..")


def test_single_process_sweep_runs_and_verifies(capsys):
    # e=2 flat mesh (subset of the 8-device farm), one tiny bucket
    exchange_study.run_child(2, 1, [2048], 1)
    line = [
        ln for ln in capsys.readouterr().out.splitlines() if ln.startswith("RESULT ")
    ][-1]
    records = json.loads(line[len("RESULT "):])
    assert {r["schedule"] for r in records} == {"a2a", "ring"}
    for r in records:
        assert r["verified"]
        assert r["bytes_received"] == r["bytes_sent"] > 0
        assert 0 < r["bytes_received_valid"] <= r["bytes_sent"]


def test_two_process_distributed_exchange(monkeypatch):
    """Both ranks run the SAME ExchangeProgram over a global 4-device
    mesh spanning 2 processes; rank 0 reports verified payloads."""
    # the children read the coordinator from the environment they
    # inherit via _spawn_child (shared spawn logic with the study)
    monkeypatch.setenv("SRT_EXCHANGE_COORD", "127.0.0.1:29815")
    procs = [
        exchange_study._spawn_child(
            ["--dist-child", str(pid), "2", "2048", "1"], 2
        )
        for pid in range(2)
    ]
    # concurrent drain: the ranks progress together, so a sequential
    # communicate() could deadlock on a filled stderr pipe
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(2) as tp:
        outs = [r[0] for r in tp.map(lambda p: p.communicate(timeout=300), procs)]
    assert all(p.returncode == 0 for p in procs), outs
    rec = exchange_study._result_line(outs[0])
    assert rec["verified"] and rec["e"] == 4 and rec["processes"] == 2
    # delta over exactly 1 timed step: this rank's 2 devices x 4 peer
    # rows of valid bytes, strictly under the global staged total
    assert 0 < rec["bytes_received_valid_local"] <= rec["total_bytes_per_step"]
