"""Device PageRank (multi-round all-to-all) vs numpy power iteration."""

import numpy as np

from sparkrdma_tpu.models.pagerank import PageRank, reference_pagerank
from sparkrdma_tpu.parallel.mesh import make_mesh


def _random_graph(n, m, seed=0):
    rng = np.random.default_rng(seed)
    edges = rng.integers(0, n, size=(m, 2), dtype=np.int64)
    return edges


def test_pagerank_matches_reference():
    n, m = 200, 1500
    edges = _random_graph(n, m)
    pr = PageRank(make_mesh())
    out = pr.run(edges, n, iters=15)
    ref = reference_pagerank(edges, n, iters=15)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-6)
    # ranks are a probability distribution
    assert abs(out.sum() - 1.0) < 1e-3


def test_pagerank_with_dangling_nodes():
    # a path graph 0 -> 1 -> 2; node 2 dangles (no out-edges)
    edges = np.array([[0, 1], [1, 2]])
    pr = PageRank(make_mesh())
    out = pr.run(edges, 3, iters=30)
    ref = reference_pagerank(edges, 3, iters=30)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-6)
    assert out[2] > out[1] > out[0]  # rank accumulates down the path


def test_pagerank_on_2d_mesh():
    n, m = 128, 800
    edges = _random_graph(n, m, seed=3)
    pr = PageRank(make_mesh(num_slices=2))
    out = pr.run(edges, n, iters=10)
    ref = reference_pagerank(edges, n, iters=10)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-6)
