"""Adaptive partition planner — plan invariants and skewed e2e wins.

The planner (shuffle/planner.py) re-cuts the reduce ranges from the
map stage's published per-partition byte totals. Two properties make
it safe to leave ON by default (DESIGN.md §18):

- every plan is a list of contiguous ``(lo, hi)`` partition-id ranges
  covering ``[0, P)`` exactly — regrouping partitions across workers
  can never duplicate or drop a (key, value) pair, and range-partition
  orderings (TeraSort) survive because range order == partition order;
- on balanced inputs the plan IS the static uniform plan, byte for
  byte — existing jobs see no churn.

The device-side twin (``plan_edges`` + ``split_sorted_edges``) is
proven on the 8-device CPU mesh: a zipf-skewed TeraSort under sampled
quantile edges sorts correctly AND beats the static top-bits plan's
wall clock (the static plan overflows its capacity class and burns
doubling retries; the ISSUE bar is overhead <= 2.5x uniform)."""

import collections
import time

import numpy as np
import pytest

from sparkrdma_tpu.obs import get_registry
from sparkrdma_tpu.shuffle.planner import (
    AdaptivePartitioner,
    capacity_from_sample,
    plan_edges,
    static_bounds,
)
from sparkrdma_tpu.utils.config import TpuShuffleConf


def _check_plan(sizes, n, ranges):
    """The well-formedness invariants every plan must satisfy."""
    p = len(sizes)
    assert len(ranges) <= max(1, n)
    covered = []
    for lo, hi in ranges:
        assert 0 <= lo <= hi <= p  # empty (k, k) ranges are legal
        covered.extend(range(lo, hi))
    # contiguous ascending coverage of [0, P) with no overlap
    assert covered == list(range(p)), (sizes, n, ranges)


def test_plan_invariants_over_random_size_vectors():
    """Property test: any size vector, any reducer count — the plan
    stays a contiguous exact cover, so the multiset of (key, value)
    pairs a reduce stage sees is preserved under regrouping."""
    rng = np.random.default_rng(42)
    planner = AdaptivePartitioner(TpuShuffleConf())
    for trial in range(300):
        p = int(rng.integers(0, 65))
        n = int(rng.integers(1, 17))
        kind = trial % 4
        if kind == 0:
            sizes = rng.integers(0, 10_000, p).tolist()
        elif kind == 1:  # zipf-ish heavy tail
            sizes = (
                rng.zipf(1.5, p).astype(np.uint64) * 1000 % (1 << 31)
            ).astype(np.int64).tolist() if p else []
        elif kind == 2:  # uniform (conservatism path)
            sizes = [1000] * p
        else:  # mostly empty with one hot partition
            sizes = [0] * p
            if p:
                sizes[int(rng.integers(0, p))] = 1_000_000
        ranges = planner.plan(sizes, n)
        if p == 0:
            assert ranges == []
            continue
        _check_plan(sizes, n, ranges)


def test_plan_regroup_preserves_pair_multiset():
    """The ISSUE's multiset property, stated directly: materialize
    per-partition (key, value) pairs, regroup them by the plan's
    ranges, and the concatenation is the exact original multiset in
    partition order."""
    rng = np.random.default_rng(7)
    planner = AdaptivePartitioner(TpuShuffleConf())
    for _ in range(50):
        p = int(rng.integers(1, 40))
        n = int(rng.integers(1, 9))
        sizes = rng.integers(0, 50, p).tolist()
        pairs = {
            pid: [(pid, int(v)) for v in rng.integers(0, 1000, sizes[pid])]
            for pid in range(p)
        }
        ranges = planner.plan([sum(v for _, v in pairs[i]) for i in range(p)], n)
        _check_plan(sizes, n, ranges)
        regrouped = []
        for lo, hi in ranges:
            for pid in range(lo, hi):
                regrouped.extend(pairs[pid])
        flat = [pair for pid in range(p) for pair in pairs[pid]]
        assert regrouped == flat  # order AND multiset preserved
        assert collections.Counter(regrouped) == collections.Counter(flat)


def test_uniform_sizes_return_static_bounds_unchanged():
    """Conservatism: balanced inputs yield byte-identical static plans
    — the reason planner-on-by-default cannot perturb existing jobs."""
    planner = AdaptivePartitioner(TpuShuffleConf())
    # p >= n: with fewer partitions than reducers each singleton range
    # already exceeds hot_factor * ideal, so the planner legitimately
    # re-cuts — conservatism is a claim about balanced DIVISIBLE loads
    for p, n in [(8, 4), (16, 8), (64, 3), (7, 7), (9, 4)]:
        assert planner.plan([1000] * p, n) == static_bounds(p, n)


def test_hot_partition_isolated_and_counted():
    """A partition holding most of the bytes gets its own 1-wide range
    and the ``planner.splits`` counter records the isolation."""
    reg = get_registry()
    before = reg.snapshot(prefix="planner.")
    planner = AdaptivePartitioner(TpuShuffleConf())
    sizes = [10, 10, 10, 10_000, 10, 10, 10, 10]
    ranges = planner.plan(sizes, 4)
    _check_plan(sizes, 4, ranges)
    assert (3, 4) in ranges, f"hot partition not isolated: {ranges}"
    delta = reg.delta(before, prefix="planner.")
    splits = sum(
        v for k, v in delta.get("counters", {}).items() if "splits" in k
    )
    assert splits >= 1
    # the hot range's load dominates; no other range should carry it
    loads = [sum(sizes[a:b]) for a, b in ranges]
    assert max(loads) == 10_000


def test_plan_edges_balance_zipf_receive_counts():
    """Quantile edges from a zipf sample balance per-shard receive
    counts where static top-bits routing concentrates them — the
    capacity estimate (== compiled slab width) shrinks accordingly."""
    rng = np.random.default_rng(3)
    keys = (rng.zipf(1.5, 65536).astype(np.uint64) * 7919 % (1 << 32)).astype(
        np.uint32
    )
    sample = keys[:4096]
    e = 8
    edges = plan_edges(sample, e)
    assert edges.shape == (e - 1,)
    assert np.all(np.diff(edges.astype(np.int64)) >= 0)
    cap_static = capacity_from_sample(sample, e, len(keys))
    cap_edges = capacity_from_sample(sample, e, len(keys), edges=edges)
    assert cap_edges < cap_static, (cap_edges, cap_static)
    # quantile routing's hottest receiver is no hotter than the static
    # top-bits plan's (duplicate keys are unsplittable ties, so an
    # absolute bound is unreachable — the RELATIVE claim is the lever)
    dest_q = np.searchsorted(edges, keys, side="right")
    dest_s = keys >> np.uint32(32 - 3)
    hot_q = np.bincount(dest_q, minlength=e).max()
    hot_s = np.bincount(dest_s.astype(np.int64), minlength=e).max()
    assert hot_q <= hot_s, (hot_q, hot_s)


def test_skewed_terasort_adaptive_correct_and_beats_static():
    """E2E on the 8-device CPU mesh (conftest.py): zipf-skewed keys,
    adaptive (sampled quantile edges) vs static (top-bits) plans. Both
    must produce the exact sorted output; the adaptive plan must win
    wall-clock — the static plan overflows its capacity class under
    skew and re-executes at doubled capacities (ISSUE bar: adaptive
    overhead <= 2.5x the uniform-keys baseline; measured ~0.85x)."""
    import jax

    from sparkrdma_tpu.models.terasort import TeraSorter

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU farm")
    sorter = TeraSorter()
    rng = np.random.default_rng(11)
    n = 1 << 17
    keys = (rng.zipf(1.5, n).astype(np.uint64) * 7919 % (1 << 32)).astype(
        np.uint32
    )
    expected = np.sort(keys)

    # correctness first, both plans, warm in the same pass
    out_adaptive = sorter.sort(keys, adaptive=True)
    out_static = sorter.sort(keys, adaptive=False)
    np.testing.assert_array_equal(out_adaptive, expected)
    np.testing.assert_array_equal(out_static, expected)

    # warm timed comparison: median of 3 to shrug scheduler noise
    def timed(**kw):
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            sorter.sort(keys, **kw)
            best = min(best, time.perf_counter() - t0)
        return best

    dt_adaptive = timed(adaptive=True)
    dt_static = timed(adaptive=False)
    assert dt_adaptive < dt_static, (
        f"adaptive {dt_adaptive:.3f}s not faster than static "
        f"{dt_static:.3f}s under zipf skew"
    )


def test_cluster_reduce_plan_regroups_hot_partition():
    """Engine-level e2e: a ClusterContext job with one hot key — the
    driver re-plans the reduce bounds from published sizes (planner
    enabled by default) and the job's output is exactly the static
    plan's output."""
    from sparkrdma_tpu.engine.cluster import ClusterContext

    def make_map(seed):
        def fn():
            # key 3 carries ~90% of the bytes
            for i in range(400):
                k = 3 if i % 10 else (seed + i) % 8
                yield (k, "x" * (40 if k == 3 else 4))

        return fn

    def collect(it):
        acc = collections.Counter()
        for k, v in it:
            acc[k] += len(v)
        return dict(acc)

    reg = get_registry()
    before = reg.snapshot(prefix="planner.")
    with ClusterContext(num_executors=2) as cc:
        parts = cc.run_map_reduce(
            [make_map(s) for s in range(4)], num_partitions=8,
            reduce_fn=collect,
        )
    merged = collections.Counter()
    for p in parts:
        merged.update(p)
    expected = collections.Counter()
    for s in range(4):
        for i in range(400):
            k = 3 if i % 10 else (s + i) % 8
            expected[k] += 40 if k == 3 else 4
    assert merged == expected
    # the skewed sizes must have actually exercised a plan() call
    # (the planner runs driver-side, i.e. in THIS process)
    delta = reg.delta(before, prefix="planner.")
    planned = sum(
        h["count"]
        for k, h in delta.get("histograms", {}).items()
        if "plan_ms" in k
    )
    assert planned >= 1, "driver never consulted the adaptive planner"
