"""Fault injection — the failure-path harness the reference lacks.

SURVEY.md §5 records that the reference has no fault injection; §4 says
the new framework must design the strategy the reference lacks. These
tests inject transport faults at the verb layer (the
`RdmaCompletionListener.onFailure` seam) and assert the resilience
chain (docs/RESILIENCE.md): transient READ failures are absorbed by
the fetcher's retry ladder with ZERO stage recomputes; only faults
that outlast the retry budget surface FetchFailedError — promptly,
never hanging the iterator (SURVEY.md §5.1 #9)."""

import threading

import pytest

from sparkrdma_tpu.engine.context import TpuContext
from sparkrdma_tpu.obs import get_registry
from sparkrdma_tpu.transport.channel import ChannelError, TpuChannel
from sparkrdma_tpu.utils.config import TpuShuffleConf

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _python_transport(monkeypatch):
    """Every injection seam in this module lives in the python verb
    layer (TpuChannel monkeypatches, the fault plan's read hooks), so
    pin the transport: the ``auto`` default resolves to native when the
    toolchain is present and would route reads around the seams."""
    monkeypatch.setattr(
        TpuShuffleConf, "transport", property(lambda self: "python")
    )


def _counter_total(snap_prefix_delta: dict) -> int:
    return sum(snap_prefix_delta.get("counters", {}).values())


@pytest.fixture
def flaky_reads(monkeypatch):
    """Fail the first N one-sided READs at post time, then heal."""
    state = {"remaining": 0, "injected": 0}
    lock = threading.Lock()
    original = TpuChannel.read_in_queue

    def wrapper(self, listener, dst_views, blocks):
        with lock:
            inject = state["remaining"] > 0
            if inject:
                state["remaining"] -= 1
                state["injected"] += 1
        if inject:
            listener.on_failure(ChannelError("injected read fault"))
            return
        return original(self, listener, dst_views, blocks)

    monkeypatch.setattr(TpuChannel, "read_in_queue", wrapper)
    return state


def test_injected_read_faults_absorbed_without_recompute(flaky_reads):
    """ISSUE acceptance: two transient READ faults complete the job with
    ZERO stage recomputes — the retry ladder absorbs them in-place."""
    reg = get_registry()
    before_retries = reg.snapshot(prefix="resilience.retries")
    before_recomputes = reg.snapshot(prefix="engine.stage_recomputes")
    flaky_reads["remaining"] = 2
    with TpuContext(num_executors=2, task_threads=2) as ctx:
        rdd = (
            ctx.parallelize(range(2000), 4)
            .map(lambda x: (x % 13, x))
            .reduce_by_key(lambda a, b: a + b, num_partitions=4)
        )
        out = dict(ctx.run_job(rdd))
    assert flaky_reads["injected"] == 2  # the faults actually fired
    expected = {}
    for x in range(2000):
        expected[x % 13] = expected.get(x % 13, 0) + x
    assert out == expected
    retries = _counter_total(reg.delta(before_retries, prefix="resilience.retries"))
    recomputes = _counter_total(
        reg.delta(before_recomputes, prefix="engine.stage_recomputes")
    )
    assert retries >= 2, f"expected the ladder to absorb both faults, got {retries}"
    assert recomputes == 0, f"expected zero stage recomputes, got {recomputes}"


def test_reduce_task_surfaces_failure_not_hang(flaky_reads):
    """With faults outlasting every retry, the job fails promptly with a
    ShuffleError instead of hanging the iterator (invariant #9)."""
    from sparkrdma_tpu.shuffle.errors import ShuffleError

    flaky_reads["remaining"] = 10**9
    with TpuContext(num_executors=2, task_threads=2) as ctx:
        rdd = (
            ctx.parallelize(range(500), 4)
            .map(lambda x: (x % 7, x))
            .group_by_key(num_partitions=4)
        )
        with pytest.raises(ShuffleError):
            ctx.run_job(rdd)


def test_send_fault_fails_location_fetch(monkeypatch):
    """An injected SEND fault on the location-fetch RPC surfaces as
    MetadataFetchFailedError (timeout path), not a hang."""
    from sparkrdma_tpu.shuffle.errors import MetadataFetchFailedError
    from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle, HashPartitioner
    from sparkrdma_tpu.shuffle.manager import TpuShuffleManager

    conf = TpuShuffleConf({"tpu.shuffle.partitionLocationFetchTimeoutMs": "400"})
    driver = TpuShuffleManager(conf, is_driver=True)
    ex0 = TpuShuffleManager(conf, is_driver=False, executor_id="exec-0")
    try:
        handle = BaseShuffleHandle(
            shuffle_id=0, num_maps=1, partitioner=HashPartitioner(1)
        )
        driver.register_shuffle(handle)
        w = ex0.get_writer(handle, 0)
        w.write(iter([("a", 1)]))
        w.stop(True)

        original = TpuChannel.send_in_queue

        def drop_fetches(self, listener, segments):
            # swallow the message entirely: the reply never comes
            listener.on_success(None)

        monkeypatch.setattr(TpuChannel, "send_in_queue", drop_fetches)
        reader = ex0.get_reader(handle, 0, 1)
        with pytest.raises(MetadataFetchFailedError):
            list(reader.read())
        monkeypatch.setattr(TpuChannel, "send_in_queue", original)
    finally:
        ex0.stop()
        driver.stop()


def test_failed_fetch_sweeps_unconsumed_streams(monkeypatch):
    """When one group fails, the iterator's failure path must CLOSE the
    already-delivered (but unconsumed) streams of other groups — and a
    group completing AFTER the failure is released on arrival.
    Registered slices / mapped windows never wait for the GC."""
    import time as _time

    import numpy as np

    import sparkrdma_tpu.shuffle.fetcher as fetcher_mod
    from sparkrdma_tpu.locations import BlockLocation, PartitionLocation
    from sparkrdma_tpu.memory.streams import MemoryviewInputStream
    from sparkrdma_tpu.shuffle.errors import FetchFailedError
    from sparkrdma_tpu.shuffle.fetcher import TpuShuffleFetcherIterator
    from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle, HashPartitioner
    from sparkrdma_tpu.shuffle.manager import TpuShuffleManager

    created = []

    class RecordingStream(MemoryviewInputStream):
        def __init__(self, *a, **kw):
            super().__init__(*a, **kw)
            created.append(self)

    monkeypatch.setattr(fetcher_mod, "MemoryviewInputStream", RecordingStream)

    # read-block cap of one block: each 48KB block is its own group
    # (the conf clamps below 64 KiB). Retries are disabled so the
    # scripted deliver/fail/late-deliver sequence stays exactly three
    # READs — this test is about the sweep, not the ladder.
    conf = TpuShuffleConf(
        {
            "tpu.shuffle.shuffleReadBlockSize": "65536",
            "tpu.shuffle.resilience.maxFetchAttempts": "1",
        }
    )
    driver = TpuShuffleManager(conf, is_driver=True)
    ex0 = TpuShuffleManager(conf, is_driver=False, executor_id="sweep-0")
    ex1 = TpuShuffleManager(conf, is_driver=False, executor_id="sweep-1")
    ex0.start_node_if_missing()
    ex1.start_node_if_missing()
    regs = []
    timers = []
    try:
        handle = BaseShuffleHandle(
            shuffle_id=41, num_maps=1, partitioner=HashPartitioner(3)
        )
        driver.register_shuffle(handle)
        rng = np.random.default_rng(11)
        locs = []
        for p in range(3):
            payload = rng.integers(0, 256, 48_000, np.uint8)
            reg = ex1.buffer_manager.get(payload.nbytes)
            regs.append(reg)
            np.frombuffer(reg.view, np.uint8, payload.nbytes)[:] = payload
            locs.append(
                PartitionLocation(
                    ex1.local_manager_id, p,
                    BlockLocation(0, payload.nbytes, reg.mkey),
                )
            )
        ex1.publish_partition_locations(41, -1, locs, num_map_outputs=1)

        state = {"n": 0}
        lock = threading.Lock()
        original = TpuChannel.read_in_queue

        def scripted(self, listener, dst_views, blocks):
            with lock:
                state["n"] += 1
                k = state["n"]
            if k == 1:
                return original(self, listener, dst_views, blocks)  # delivers
            if k == 2:
                listener.on_failure(ChannelError("injected sweep fault"))
                return
            # third group: completes AFTER the failure surfaced
            t = threading.Timer(
                0.6, lambda: original(self, listener, dst_views, blocks)
            )
            t.daemon = True
            timers.append(t)
            t.start()

        monkeypatch.setattr(TpuChannel, "read_in_queue", scripted)
        it = TpuShuffleFetcherIterator(ex0, handle, 0, 3)
        # streams RETURNED by next() are the caller's to close (the
        # reader's per-stream finally); the sweep owns only unreturned
        # ones — mirror that contract here
        returned = []
        with pytest.raises(FetchFailedError):
            while True:
                returned.append(it.next())
        for _pid, s in returned:
            s.close()
        # the resolver thread issues the groups concurrently with the
        # failing next(): wait for all three to have been posted
        deadline = _time.time() + 5
        while _time.time() < deadline and state["n"] < 3:
            _time.sleep(0.05)
        assert state["n"] == 3, "expected three distinct fetch groups"
        # group 1 delivered before the failure; group 3 delivers late —
        # BOTH must end up closed without anyone consuming them
        deadline = _time.time() + 5
        while _time.time() < deadline:
            if len(created) >= 2 and all(s.closed for s in created):
                break
            _time.sleep(0.05)
        assert created, "no streams were ever delivered"
        assert all(s.closed for s in created), (
            f"{sum(not s.closed for s in created)} unconsumed stream(s) "
            "left open after the failure sweep"
        )
    finally:
        for t in timers:
            t.cancel()
        for reg in regs:
            ex1.buffer_manager.put(reg)
        ex0.stop()
        ex1.stop()
        driver.stop()


# ----------------------------------------------------------------------
# first-class fault plans (sparkrdma_tpu.testing.faults)
# ----------------------------------------------------------------------
def test_fault_plan_transient_reads_absorbed(monkeypatch):
    """Same acceptance as the monkeypatch test, driven by the subsystem:
    a `read:fail:2` plan completes with zero recomputes."""
    from sparkrdma_tpu.testing import faults

    reg = get_registry()
    before_recomputes = reg.snapshot(prefix="engine.stage_recomputes")
    with faults.installed("read:fail:2") as plan:
        with TpuContext(num_executors=2, task_threads=2) as ctx:
            rdd = (
                ctx.parallelize(range(1000), 4)
                .map(lambda x: (x % 11, x))
                .reduce_by_key(lambda a, b: a + b, num_partitions=4)
            )
            out = dict(ctx.run_job(rdd))
    assert plan.injected_count("read", "fail") == 2
    expected = {}
    for x in range(1000):
        expected[x % 11] = expected.get(x % 11, 0) + x
    assert out == expected
    recomputes = _counter_total(
        reg.delta(before_recomputes, prefix="engine.stage_recomputes")
    )
    assert recomputes == 0


def test_fault_plan_exhaustion_surfaces_promptly():
    """`read:fail:0` (every READ fails, forever) with a tight retry
    budget: the job raises ShuffleError promptly instead of hanging."""
    import time as _time

    from sparkrdma_tpu.shuffle.errors import ShuffleError
    from sparkrdma_tpu.testing import faults

    conf = TpuShuffleConf(
        {
            "tpu.shuffle.resilience.maxFetchAttempts": "2",
            "tpu.shuffle.resilience.retryBackoffMs": "5",
            "tpu.shuffle.resilience.retryBackoffMaxMs": "10",
        }
    )
    with faults.installed("read:fail:0"):
        t0 = _time.monotonic()
        with TpuContext(num_executors=2, conf=conf, task_threads=2) as ctx:
            rdd = (
                ctx.parallelize(range(200), 2)
                .map(lambda x: (x % 5, x))
                .group_by_key(num_partitions=2)
            )
            with pytest.raises(ShuffleError):
                ctx.run_job(rdd)
        assert _time.monotonic() - t0 < 60


def test_fault_plan_corruption_detected_and_refetched():
    """ISSUE acceptance: a corrupted remote block is caught by its
    checksum and transparently refetched — correct results, and the
    checksum-failure counter proves detection actually happened."""
    from sparkrdma_tpu.testing import faults

    reg = get_registry()
    before = reg.snapshot(prefix="resilience.checksum_failures")
    before_recomputes = reg.snapshot(prefix="engine.stage_recomputes")
    with faults.installed("read:corrupt:1", seed=3) as plan:
        with TpuContext(num_executors=2, task_threads=2) as ctx:
            rdd = (
                ctx.parallelize(range(1500), 4)
                .map(lambda x: (x % 9, x * 2))
                .reduce_by_key(lambda a, b: a + b, num_partitions=4)
            )
            out = dict(ctx.run_job(rdd))
    assert plan.injected_count("read", "corrupt") == 1
    expected = {}
    for x in range(1500):
        expected[x % 9] = expected.get(x % 9, 0) + x * 2
    assert out == expected
    detected = _counter_total(
        reg.delta(before, prefix="resilience.checksum_failures")
    )
    assert detected >= 1, "corruption fired but the checksum never caught it"
    recomputes = _counter_total(
        reg.delta(before_recomputes, prefix="engine.stage_recomputes")
    )
    assert recomputes == 0, "corruption should be absorbed below the engine"


def test_stage_seam_corrupt_in_decode_detected_and_reordered():
    """The ``stage`` fault seam (DESIGN.md §16): a block corrupted in
    the reduce pipeline's DECODE stage — after the wire delivered it
    intact, so no transport-level gate can see it — is caught by
    verify_host_block's checksum, refetched once, and the pipeline
    still delivers every group in source order with correct bytes."""
    import numpy as np

    from sparkrdma_tpu.locations import BlockLocation, PartitionLocation
    from sparkrdma_tpu.shuffle.device_io import DeviceShuffleIO
    from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
    from sparkrdma_tpu.shuffle.reader.pipeline import ReduceTaskPipeline
    from sparkrdma_tpu.testing import faults

    reg = get_registry()
    before_detect = reg.snapshot(prefix="resilience.checksum_failures")
    before_retry = reg.snapshot(prefix="resilience.retries")
    conf = TpuShuffleConf()
    driver = TpuShuffleManager(conf, is_driver=True)
    ex0 = TpuShuffleManager(conf, is_driver=False, executor_id="stg-0")
    ex1 = TpuShuffleManager(conf, is_driver=False, executor_id="stg-1")
    ex1.start_node_if_missing()
    regs = []
    try:
        rng = np.random.default_rng(5)
        payloads = []
        locs = []
        for p in range(4):
            payload = rng.integers(0, 256, 48_000, np.uint8).tobytes()
            payloads.append(payload)
            buf = ex1.buffer_manager.get(len(payload))
            regs.append(buf)
            np.frombuffer(buf.view, np.uint8, len(payload))[:] = (
                np.frombuffer(payload, np.uint8)
            )
            locs.append(
                PartitionLocation(
                    ex1.local_manager_id, p,
                    BlockLocation(0, len(payload), buf.mkey),
                )
            )
        ex1.publish_partition_locations(77, -1, locs, num_map_outputs=1)

        io = DeviceShuffleIO(ex0)

        def fetch_group(r):
            return io.fetch_host_blocks(77, r, r + 1, timeout_s=30)[r]

        def verify_group(r, blocks):
            # the decode-stage gate: the seam below corrupts ONE
            # fetched payload right here, past every transport check
            return [io.verify_host_block(hb) for hb in blocks]

        def take_bytes(r, blocks):
            out = [bytes(hb.data) for hb in blocks]
            for hb in blocks:
                hb.release()
            return (r, out)

        def discard(stage, _item, value):
            if stage in ("fetch", "decode") and value:
                for hb in value:
                    hb.release()

        pipe = ReduceTaskPipeline(
            fetch_group, verify_group, take_bytes, None,
            parallelism=2, depth=2, double_buffer=False,
            role="t-stage-seam", discard_fn=discard,
        )
        with faults.installed("stage:corrupt:1:stage=decode", seed=7) as plan:
            results = list(pipe.stream(range(4)))
        try:
            assert plan.injected_count("stage", "corrupt") == 1, (
                "the decode-stage corruption never fired"
            )
            # in-order delivery AND correct bytes despite the refetch
            assert [r for r, _ in results] == [0, 1, 2, 3]
            for r, blobs in results:
                assert blobs == [payloads[r]], f"group {r} bytes differ"
            detected = _counter_total(
                reg.delta(before_detect, prefix="resilience.checksum_failures")
            )
            retried = _counter_total(
                reg.delta(before_retry, prefix="resilience.retries")
            )
            assert detected >= 1, "corruption fired but never detected"
            assert retried >= 1, "detection without a refetch"
        finally:
            io.stop()
    finally:
        for buf in regs:
            ex1.buffer_manager.put(buf)
        ex0.stop()
        ex1.stop()
        driver.stop()


def test_circuit_breaker_opens_and_fails_fast():
    """Persistent failures open the per-peer breaker; subsequent fetch
    attempts fail fast (counter proves the short-circuit) instead of
    burning the full retry ladder per group."""
    from sparkrdma_tpu.shuffle.errors import ShuffleError
    from sparkrdma_tpu.testing import faults

    reg = get_registry()
    before = reg.snapshot(prefix="resilience.circuit_fail_fast")
    conf = TpuShuffleConf(
        {
            "tpu.shuffle.resilience.maxFetchAttempts": "2",
            "tpu.shuffle.resilience.retryBackoffMs": "5",
            "tpu.shuffle.resilience.retryBackoffMaxMs": "10",
            "tpu.shuffle.resilience.circuitFailureThreshold": "2",
            "tpu.shuffle.resilience.circuitOpenMs": "60000",
        }
    )
    with faults.installed("read:fail:0"):
        with TpuContext(num_executors=2, conf=conf, task_threads=2) as ctx:
            rdd = (
                ctx.parallelize(range(400), 8)
                .map(lambda x: (x % 17, x))
                .reduce_by_key(lambda a, b: a + b, num_partitions=8)
            )
            with pytest.raises(ShuffleError):
                ctx.run_job(rdd)
    fail_fast = _counter_total(
        reg.delta(before, prefix="resilience.circuit_fail_fast")
    )
    assert fail_fast >= 1, "expected at least one circuit-open fail-fast"


# ----------------------------------------------------------------------
# push/merge plane seams (shuffle/merge.py, DESIGN.md §18)
# ----------------------------------------------------------------------
def _chunked_push_shuffle(push_on=True):
    """One 2-executor chunked-agg shuffle (the writer method carrying
    the push hooks); returns the reduce output as sorted (k, v) pairs
    so runs are comparable byte-for-byte at the record level."""
    from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle, HashPartitioner
    from sparkrdma_tpu.shuffle.manager import TpuShuffleManager

    conf = TpuShuffleConf(
        {
            "tpu.shuffle.shuffleWriteMethod": "chunkedpartitionagg",
            "tpu.shuffle.shuffleWriteBlockSize": "65536",
            "tpu.shuffle.shuffleReadBlockSize": "65536",
            "tpu.shuffle.push.enabled": "true" if push_on else "false",
        }
    )
    driver = TpuShuffleManager(conf, is_driver=True)
    ex0 = TpuShuffleManager(conf, is_driver=False, executor_id="pfi-0")
    ex1 = TpuShuffleManager(conf, is_driver=False, executor_id="pfi-1")
    try:
        handle = BaseShuffleHandle(
            shuffle_id=0, num_maps=4, partitioner=HashPartitioner(5)
        )
        driver.register_shuffle(handle)
        for map_id, ex in [(0, ex0), (1, ex0), (2, ex1), (3, ex1)]:
            w = ex.get_writer(handle, map_id)
            w.write(
                iter(
                    (f"key-{(map_id * 3000 + i) % 397}", map_id * 3000 + i)
                    for i in range(3000)
                )
            )
            assert w.stop(True) is not None
        ex0.finalize_maps(0)
        ex1.finalize_maps(0)
        out = []
        for ex, (lo, hi) in [(ex0, (0, 3)), (ex1, (3, 5))]:
            reader = ex.get_reader(handle, lo, hi)
            out.extend(reader.read())
        return sorted(out)
    finally:
        ex0.stop()
        ex1.stop()
        driver.stop()


def test_push_drop_falls_back_to_originals_byte_identical():
    """ISSUE acceptance (`push:drop:N`): lost push messages leave the
    affected partitions' coverage incomplete — no seal, originals stay
    authoritative, and the shuffle output is exactly the non-push
    run's output. Best-effort means a drop is never an error."""
    from sparkrdma_tpu.testing import faults

    baseline = _chunked_push_shuffle(push_on=False)
    with faults.installed("push:drop:3") as plan:
        out = _chunked_push_shuffle(push_on=True)
    assert plan.injected_count("push", "drop") == 3, (
        "the drop seam never fired — pushes did not flow"
    )
    assert out == baseline


def test_two_tenant_fault_isolation(monkeypatch):
    """Tenancy acceptance (DESIGN.md §19): persistent READ faults scoped
    to ONE tenant's tasks fail that tenant's job — and ONLY that
    tenant's breakers. A concurrent quiet tenant sharing the same
    executors, pools, and peers completes correctly, and none of its
    tenant-scoped breaker keys ever open."""
    from sparkrdma_tpu import tenancy
    from sparkrdma_tpu.shuffle.errors import ShuffleError

    state = {"injected": 0}
    lock = threading.Lock()
    original = TpuChannel.read_in_queue

    def noisy_only(self, listener, dst_views, blocks):
        # the read is posted from a tenant-scoped thread (fair-share
        # worker or the fetcher's re-scoped retry rung), so the current
        # scope names the owning tenant
        if tenancy.current_tenant() == "noisy":
            with lock:
                state["injected"] += 1
            listener.on_failure(ChannelError("injected noisy-tenant fault"))
            return
        return original(self, listener, dst_views, blocks)

    monkeypatch.setattr(TpuChannel, "read_in_queue", noisy_only)
    conf = TpuShuffleConf(
        {
            "tpu.shuffle.resilience.maxFetchAttempts": "2",
            "tpu.shuffle.resilience.retryBackoffMs": "5",
            "tpu.shuffle.resilience.retryBackoffMaxMs": "10",
            "tpu.shuffle.resilience.circuitFailureThreshold": "2",
            "tpu.shuffle.resilience.circuitOpenMs": "60000",
        }
    )
    results = {}
    errors = {}

    with TpuContext(num_executors=2, conf=conf, task_threads=4) as ctx:
        def job(tenant, n, mod):
            try:
                rdd = (
                    ctx.parallelize(range(n), 4)
                    .map(lambda x: (x % mod, 1))
                    .reduce_by_key(lambda a, b: a + b, num_partitions=4)
                )
                results[tenant] = dict(ctx.run_job(rdd, tenant=tenant))
            except Exception as e:  # noqa: BLE001 — inspected below
                errors[tenant] = e

        threads = [
            threading.Thread(target=job, args=("noisy", 1200, 5)),
            threading.Thread(target=job, args=("quiet", 2000, 9)),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)

        # the noisy tenant's job fails (its faults outlast the budget)...
        assert isinstance(errors.get("noisy"), ShuffleError), (
            f"noisy tenant should fail with ShuffleError, got {errors}"
        )
        assert state["injected"] >= 2
        # ...while the quiet tenant's concurrent job is untouched
        assert "quiet" not in errors, f"quiet tenant failed: {errors.get('quiet')}"
        assert results["quiet"] == {
            k: len(range(k, 2000, 9)) for k in range(9)
        }
        # breaker isolation: noisy-scoped keys opened; every breaker
        # the quiet tenant touched stays closed
        states = {}
        for mgr in ctx.executors:
            states.update(mgr.health.states())
        assert any(
            k.startswith("noisy:") and v == "open" for k, v in states.items()
        ), f"expected an open noisy-scoped breaker, got {states}"
        for key, st in states.items():
            if key.startswith("quiet:") or ":" not in key:
                assert st == "closed", (
                    f"fault bled across tenants: breaker {key} is {st}"
                )


def test_push_corrupt_merged_segment_detected_then_fallback():
    """ISSUE acceptance (`push:corrupt:1`): a merged segment corrupted
    AFTER its checksum tag was computed must be caught by the reduce
    path's ordinary checksum gate and answered with a fallback to the
    original per-map blocks — detect -> fallback -> byte-identical
    output, with the detection and fallback counters as proof."""
    from sparkrdma_tpu.testing import faults

    reg = get_registry()
    baseline = _chunked_push_shuffle(push_on=False)
    before_detect = reg.snapshot(prefix="resilience.checksum_failures")
    before_fallback = reg.snapshot(prefix="push.fallbacks")
    with faults.installed("push:corrupt:1", seed=13) as plan:
        out = _chunked_push_shuffle(push_on=True)
    assert plan.injected_count("push", "corrupt") == 1, (
        "the seal-corruption seam never fired — no segment sealed"
    )
    assert out == baseline
    detected = _counter_total(
        reg.delta(before_detect, prefix="resilience.checksum_failures")
    )
    fallbacks = _counter_total(
        reg.delta(before_fallback, prefix="push.fallbacks")
    )
    assert detected >= 1, "corruption fired but the checksum gate missed it"
    assert fallbacks >= 1, "detection without a fallback to the originals"


def test_block_corrupt_header_detected_and_refetched():
    """The ``block`` fault seam (DESIGN.md §25): one byte flipped inside
    a landed columnar frame's header span, BEFORE the fetcher's checksum
    gate runs. The gate must detect it (a corrupted dtype code or offset
    table would mis-alias every zero-copy column view), the retry ladder
    must refetch, and the reduce path must deliver byte-identical rows."""
    import numpy as np

    from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle, HashPartitioner
    from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
    from sparkrdma_tpu.testing import faults

    reg = get_registry()
    before_detect = reg.snapshot(prefix="resilience.checksum_failures")
    before_retry = reg.snapshot(prefix="resilience.retries")
    conf = TpuShuffleConf(
        {
            "tpu.shuffle.shuffleWriteMethod": "wrapper",
            "tpu.shuffle.block.format": "columnar",
        }
    )
    driver = TpuShuffleManager(conf, is_driver=True)
    ex0 = TpuShuffleManager(conf, is_driver=False, executor_id="blk-0")
    ex1 = TpuShuffleManager(conf, is_driver=False, executor_id="blk-1")
    try:
        handle = BaseShuffleHandle(
            shuffle_id=0, num_maps=2, partitioner=HashPartitioner(2)
        )
        driver.register_shuffle(handle)
        expected = {}
        for map_id, ex in [(0, ex0), (1, ex1)]:
            recs = [
                (np.uint32((map_id * 5000 + i) % 499), np.int64(i * 7))
                for i in range(3000)
            ]
            for k, v in recs:
                expected.setdefault(int(k), []).append(int(v))
            w = ex.get_writer(handle, map_id)
            w.write(iter(recs))
            assert w.stop(True) is not None
        ex0.finalize_maps(0)
        ex1.finalize_maps(0)
        got = {}
        with faults.installed("block:corrupt_header:1", seed=17) as plan:
            # ex0 reads both partitions: ex1's blocks arrive as remote
            # one-sided READs into writable registered slices — the
            # seam's target
            for k, v in ex0.get_reader(handle, 0, 2).read():
                got.setdefault(int(k), []).append(int(v))
        assert plan.injected_count("block", "corrupt_header") == 1, (
            "the columnar-header seam never fired — no writable "
            "columnar frame reached the checksum gate"
        )
    finally:
        ex1.stop()
        ex0.stop()
        driver.stop()
    assert set(got) == set(expected)
    for k in expected:
        assert sorted(got[k]) == sorted(expected[k]), f"mismatch for key {k}"
    detected = _counter_total(
        reg.delta(before_detect, prefix="resilience.checksum_failures")
    )
    retries = _counter_total(reg.delta(before_retry, prefix="resilience.retries"))
    assert detected >= 1, "header corruption fired but the gate missed it"
    assert retries >= 1, "detection without a refetch"
