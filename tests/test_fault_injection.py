"""Fault injection — the failure-path harness the reference lacks.

SURVEY.md §5 records that the reference has no fault injection; §4 says
the new framework must design the strategy the reference lacks. These
tests inject transport faults at the verb layer (the
`RdmaCompletionListener.onFailure` seam) and assert the degradation
chain: failed READ -> FetchFailedError -> engine recomputes the stage
-> correct results (SURVEY.md §5.1 #9: failures degrade to retry
machinery, never hang the iterator)."""

import threading

import pytest

from sparkrdma_tpu.engine.context import TpuContext
from sparkrdma_tpu.transport.channel import ChannelError, TpuChannel
from sparkrdma_tpu.utils.config import TpuShuffleConf


@pytest.fixture
def flaky_reads(monkeypatch):
    """Fail the first N one-sided READs at post time, then heal."""
    state = {"remaining": 0, "injected": 0}
    lock = threading.Lock()
    original = TpuChannel.read_in_queue

    def wrapper(self, listener, dst_views, blocks):
        with lock:
            inject = state["remaining"] > 0
            if inject:
                state["remaining"] -= 1
                state["injected"] += 1
        if inject:
            listener.on_failure(ChannelError("injected read fault"))
            return
        return original(self, listener, dst_views, blocks)

    monkeypatch.setattr(TpuChannel, "read_in_queue", wrapper)
    return state


def test_injected_read_fault_triggers_recompute(flaky_reads):
    flaky_reads["remaining"] = 2
    with TpuContext(num_executors=2, task_threads=2) as ctx:
        rdd = (
            ctx.parallelize(range(2000), 4)
            .map(lambda x: (x % 13, x))
            .reduce_by_key(lambda a, b: a + b, num_partitions=4)
        )
        out = dict(ctx.run_job(rdd))
    assert flaky_reads["injected"] == 2  # the faults actually fired
    expected = {}
    for x in range(2000):
        expected[x % 13] = expected.get(x % 13, 0) + x
    assert out == expected


def test_reduce_task_surfaces_failure_not_hang(flaky_reads):
    """With faults outlasting every retry, the job fails promptly with a
    ShuffleError instead of hanging the iterator (invariant #9)."""
    from sparkrdma_tpu.shuffle.errors import ShuffleError

    flaky_reads["remaining"] = 10**9
    with TpuContext(num_executors=2, task_threads=2) as ctx:
        rdd = (
            ctx.parallelize(range(500), 4)
            .map(lambda x: (x % 7, x))
            .group_by_key(num_partitions=4)
        )
        with pytest.raises(ShuffleError):
            ctx.run_job(rdd)


def test_send_fault_fails_location_fetch(monkeypatch):
    """An injected SEND fault on the location-fetch RPC surfaces as
    MetadataFetchFailedError (timeout path), not a hang."""
    from sparkrdma_tpu.shuffle.errors import MetadataFetchFailedError
    from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle, HashPartitioner
    from sparkrdma_tpu.shuffle.manager import TpuShuffleManager

    conf = TpuShuffleConf({"tpu.shuffle.partitionLocationFetchTimeoutMs": "400"})
    driver = TpuShuffleManager(conf, is_driver=True)
    ex0 = TpuShuffleManager(conf, is_driver=False, executor_id="exec-0")
    try:
        handle = BaseShuffleHandle(
            shuffle_id=0, num_maps=1, partitioner=HashPartitioner(1)
        )
        driver.register_shuffle(handle)
        w = ex0.get_writer(handle, 0)
        w.write(iter([("a", 1)]))
        w.stop(True)

        original = TpuChannel.send_in_queue

        def drop_fetches(self, listener, segments):
            # swallow the message entirely: the reply never comes
            listener.on_success(None)

        monkeypatch.setattr(TpuChannel, "send_in_queue", drop_fetches)
        reader = ex0.get_reader(handle, 0, 1)
        with pytest.raises(MetadataFetchFailedError):
            list(reader.read())
        monkeypatch.setattr(TpuChannel, "send_in_queue", original)
    finally:
        ex0.stop()
        driver.stop()
