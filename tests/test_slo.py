"""SLO engine + automated diagnosis (ISSUE 16): hand-computed burn-rate
math, objective window semantics, page/warn transition bookkeeping,
counter-reset safety across heartbeat baselines, liveness breaches under
real executor loss, the diagnosis rubric, the CLI renderer, and the
deterministic chaos e2e (seeded stage delay -> latency breach -> the
top-ranked cause names the injected executor and stage category)."""

import json
import time

import pytest

from sparkrdma_tpu.obs import (
    Heartbeater,
    MetricsRegistry,
    TelemetryHub,
    TimeSeriesRing,
    render_openmetrics,
)
from sparkrdma_tpu.obs.diagnose import build_diagnosis, render
from sparkrdma_tpu.obs.slo import (
    Breach,
    Objective,
    burn_rate,
    exceedance,
    judge,
    multi_window_burn,
)
from sparkrdma_tpu.testing import faults
from sparkrdma_tpu.utils.config import TpuShuffleConf


# ---------------------------------------------------------------------------
# pure burn-rate math, hand-computed
# ---------------------------------------------------------------------------

def test_burn_rate_hand_computed():
    # 10 bad / 200 total = 5% observed; 5% / 1% budget = 5x burn
    assert burn_rate([(5, 100), (5, 100)], 0.01) == pytest.approx(5.0)
    assert burn_rate([], 0.01) == 0.0
    assert burn_rate([(0, 0)], 0.01) == 0.0  # idle: burns nothing
    assert burn_rate([(1, 10)], 0.0) == 0.0  # degenerate budget


def test_multi_window_fast_burn_fires_only_while_still_burning():
    budget, long_n, thresh = 0.01, 8, 8.0
    # sustained 10% bad: both windows read 10x >= 8x -> page
    hot = [(10, 100)] * 8
    b_long, b_short, fired = multi_window_burn(hot, budget, long_n, thresh)
    assert (b_long, b_short, fired) == (pytest.approx(10.0),
                                        pytest.approx(10.0), True)
    # recovery: the long average is still high (60/800/.01 = 7.5, and
    # with heavier history 600/800/.01 = 75) but the short window
    # (8 // 3 = 2 buckets) is clean -> the alert must drop
    recovered = [(100, 100)] * 6 + [(0, 100)] * 2
    b_long, b_short, fired = multi_window_burn(
        recovered, budget, long_n, thresh)
    assert b_long == pytest.approx(75.0)
    assert b_short == 0.0
    assert fired is False


def test_multi_window_slow_burn_warns_below_fast_threshold():
    budget = 0.01
    pts = [(3, 100)] * 32  # steady 3% bad = 3x burn
    b_long, b_short, warn = multi_window_burn(pts, budget, 32, 2.0)
    assert b_long == pytest.approx(3.0)
    assert b_short == pytest.approx(3.0)  # last 32 // 3 = 10 buckets
    assert warn is True
    _, _, page = multi_window_burn(pts, budget, 8, 8.0)
    assert page is False  # a slow leak never fast-pages


def test_exceedance_snaps_threshold_up_to_bucket_bound():
    buckets = {"le_100": 3, "le_200": 2, "overflow": 1}
    # 150 snaps UP to 200: only events provably above 200 are bad
    assert exceedance(buckets, 150) == (1, 6)
    # exactly on a bound: le_200 sits above it
    assert exceedance(buckets, 100) == (3, 6)
    # above every bound: only the overflow bucket can prove exceedance
    assert exceedance(buckets, 1000) == (1, 6)
    assert exceedance({}, 100) == (0, 0)


def test_judge_comparators_and_unmeasured_bars():
    assert judge("o", 5, 10, "le")["ok"] is True
    assert judge("o", 11, 10, "le")["ok"] is False
    assert judge("o", 11, 10, "ge")["ok"] is True
    assert judge("o", 0, 0, "eq")["ok"] is True
    v = judge("o", None, 10, "le")
    assert v["ok"] is False and "unavailable" in v["note"]
    with pytest.raises(ValueError):
        judge("o", 1, 1, "gt")


# ---------------------------------------------------------------------------
# objective window semantics
# ---------------------------------------------------------------------------

def _window(counters=None, hists=None):
    ring = TimeSeriesRing(size=4, interval_ms=100)
    ring.append(100, 1, counters=counters or {}, histograms=hists or {})
    return ring.windows()[0]


def test_ratio_objective_clamps_total_below_bad():
    obj = Objective("errs", "ratio", bad=("transport.read_errors",),
                    total=("transport.reads",))
    w = _window(counters={"transport.read_errors{role=e0}": 5,
                          "transport.reads{role=e0}": 3})
    # a total series that excludes failures can undercount: the ratio
    # must still cap at 1.0, not overshoot the burn scale
    assert obj.window_events(w, 100) == (5.0, 5.0)


def test_latency_objective_skips_unbucketed_payloads():
    obj = Objective("p99", "latency", series=("engine.task_ms",),
                    threshold_ms=100.0)
    legacy = _window(hists={"engine.task_ms{role=e0}":
                            {"count": 4, "sum": 4000.0}})
    assert obj.window_events(legacy, 100) == (0.0, 0.0)
    bucketed = _window(hists={"engine.task_ms{role=e0}":
                              {"count": 10, "sum": 9000.0,
                               "buckets": {"le_100": 1, "le_2000": 9}}})
    assert obj.window_events(bucketed, 100) == (9.0, 10.0)


def test_tenant_objective_matches_default_tenant_fallback():
    from sparkrdma_tpu.tenancy import DEFAULT_TENANT

    obj = Objective("p99-t0", "latency", series=("engine.task_ms",),
                    tenant="tenant-0", threshold_ms=100.0)
    assert obj.matches(
        "engine.task_ms{role=e0,tenant=tenant-0}", obj.series)
    assert not obj.matches("engine.task_ms{role=e0}", obj.series)
    dflt = Objective("p99-d", "latency", series=("engine.task_ms",),
                     tenant=DEFAULT_TENANT, threshold_ms=100.0)
    # a key with no tenant label is the default tenant's traffic
    assert dflt.matches("engine.task_ms{role=e0}", dflt.series)


def test_latency_budget_derived_from_percentile():
    obj = Objective("p95", "latency", series=("x",), threshold_ms=10,
                    percentile=95.0)
    assert obj.budget == pytest.approx(0.05)


# ---------------------------------------------------------------------------
# engine: transitions, recovery, escalation, reset safety, liveness
# ---------------------------------------------------------------------------

def _hub(interval_ms=100, ring_size=64):
    reg = MetricsRegistry()
    hub = TelemetryHub(role="drv", registry=reg, interval_ms=interval_ms,
                       ring_size=ring_size)
    return reg, hub


def _lat_payload(eid, seq, wall, bad, good):
    # Full bucket vector, zeros kept — the same shape Heartbeater ships
    # (exceedance snaps thresholds to the bounds present in the keys, so
    # pruning zero buckets would silently move the bar).
    buckets = {"le_100": good, "le_2000": bad}
    return {"v": 1, "executor_id": eid, "seq": seq, "wall_ms": wall,
            "interval_ms": 100, "counters": {}, "gauges": {},
            "histograms": {f"engine.task_ms{{role={eid}}}":
                           {"count": bad + good,
                            "sum": float(bad * 1200 + good * 5),
                            "buckets": buckets}}}


def test_engine_latency_page_is_one_transition_then_recovers_then_repages():
    reg, hub = _hub()
    try:
        hub.slo.add(Objective("task-p99", "latency",
                              series=("engine.task_ms",), threshold_ms=100,
                              fast_windows=4, slow_windows=8))
        seq = 0
        for i in range(2):  # two buckets, 90% above threshold
            seq += 1
            hub.ingest(_lat_payload("e0", seq, seq * 100, bad=9, good=1))
        new = hub.slo.evaluate(now_ms=seq * 100)
        assert [b.severity for b in new] == ["page"]
        assert new[0].objective == "task-p99"
        # burn over 2 active buckets: 18/20 = 90% over a 1% budget
        assert new[0].burn_fast == pytest.approx(90.0)
        # sustained breach: same severity is NOT a new transition
        seq += 1
        hub.ingest(_lat_payload("e0", seq, seq * 100, bad=9, good=1))
        assert hub.slo.evaluate(now_ms=seq * 100) == []
        # recovery: 4 clean buckets push the fast window under threshold
        for _ in range(4):
            seq += 1
            hub.ingest(_lat_payload("e0", seq, seq * 100, bad=0, good=10))
        assert hub.slo.evaluate(now_ms=seq * 100) == []
        assert hub.slo.summary()["breaching"] == 0
        # relapse: a fresh transition records a SECOND breach
        for _ in range(2):
            seq += 1
            hub.ingest(_lat_payload("e0", seq, seq * 100, bad=10, good=0))
        new = hub.slo.evaluate(now_ms=seq * 100)
        assert [b.severity for b in new] == ["page"]
        assert hub.slo.breach_total == 2
        snap = reg.snapshot()
        assert snap["counters"][
            "slo.breaches{objective=task-p99,role=drv,severity=page}"] == 2
        # the plane's own families render through OpenMetrics cleanly
        text = render_openmetrics(snap)
        assert "slo_evaluations_total" in text
        assert "slo_burn_rate" in text
        assert reg.family_violations() == []
    finally:
        hub.stop()


def test_engine_warn_then_page_escalation_records_both():
    _, hub = _hub()
    try:
        hub.slo.add(Objective("task-p99", "latency",
                              series=("engine.task_ms",), threshold_ms=100,
                              fast_windows=4, slow_windows=8))
        seq = 0
        # 8 buckets at 4% exceedance: slow burn 4x >= 2x (warn), fast
        # burn 4x < 8x (no page)
        for _ in range(8):
            seq += 1
            hub.ingest(_lat_payload("e0", seq, seq * 100, bad=4, good=96))
        new = hub.slo.evaluate(now_ms=seq * 100)
        assert [b.severity for b in new] == ["warn"]
        # then the incident gets worse: 20% exceedance pages
        for _ in range(4):
            seq += 1
            hub.ingest(_lat_payload("e0", seq, seq * 100, bad=20, good=80))
        new = hub.slo.evaluate(now_ms=seq * 100)
        assert [b.severity for b in new] == ["page"]
        assert [b.severity for b in hub.slo.breaches] == ["warn", "page"]
    finally:
        hub.stop()


def test_engine_burn_math_survives_counter_reset_across_beats():
    reg, hub = _hub()
    try:
        hb = Heartbeater(reg, "e0", interval_ms=100, send=hub.ingest)
        h = reg.histogram("engine.task_ms", role="e0")
        for _ in range(3):
            h.observe(700)
        hb.beat()
        reg.reset()  # zeroed in place: next delta must NOT go negative
        h.observe(900)
        hb.beat()
        obj = Objective("task-p99", "latency",
                        series=("engine.task_ms",), threshold_ms=500)
        pts = hub.slo.burn_points(obj)
        assert all(bad >= 0 and total >= 0 for _, bad, total in pts)
        # 3 pre-reset + 1 post-reset observation survive the reset (the
        # moving baseline restarts instead of going negative), and all
        # four land above the 500 ms threshold
        assert sum(t for _, _, t in pts) == 4.0
        assert sum(b for _, b, _ in pts) == 4.0
    finally:
        hub.stop()


def test_engine_liveness_breach_names_dead_executor_and_diagnoses():
    _, hub = _hub()
    try:
        base = {"v": 1, "interval_ms": 100, "counters": {}, "gauges": {},
                "histograms": {}}
        hub.ingest(dict(base, executor_id="e0", seq=1, wall_ms=100))
        hub.ingest(dict(base, executor_id="e1", seq=1, wall_ms=110))
        # e1 goes silent; e0's later heartbeat advances the hub clock
        # past the 2.5-interval horizon and flags it
        hub.ingest(dict(base, executor_id="e0", seq=2, wall_ms=600))
        assert hub.missed_executors() == ["e1"]
        new = hub.slo.evaluate(now_ms=600)
        assert [(b.objective, b.severity, b.executor) for b in new] == [
            ("executor-liveness", "page", "e1")]
        # sustained outage: no second transition
        assert hub.slo.evaluate(now_ms=700) == []
        # the breach hook built a diagnosis naming the dead executor
        diags = hub.slo.summary()["diagnosis_records"]
        assert diags and diags[-1]["top_cause"]["cause"] == "dead-executor"
        assert diags[-1]["top_cause"]["executor"] == "e1"
        # resume clears the per-executor breach state (wall 840 keeps
        # e0's 600 ms beat inside the 250 ms staleness horizon)
        hub.ingest(dict(base, executor_id="e1", seq=2, wall_ms=840))
        assert hub.missed_executors() == []
        assert hub.slo.evaluate(now_ms=840) == []
        assert hub.slo.summary()["breaching"] == 0
    finally:
        hub.stop()


def test_conf_installs_tenant_objectives_and_gates_on_nonzero_bars():
    conf = TpuShuffleConf({
        "tpu.shuffle.obs.slo.taskP99Ms": "250",
        "tpu.shuffle.obs.slo.tenant.tenant-7.taskP99Ms": "90",
        "tpu.shuffle.tenancy.weights": "tenant-a:2,tenant-b:1",
    })
    reg = MetricsRegistry()
    hub = TelemetryHub(role="drv", registry=reg, conf=conf, interval_ms=100)
    try:
        names = set(hub.slo.objectives)
        assert {"fetch-error-ratio", "executor-liveness",
                "task-p99"} <= names
        # declared fair-share tenants inherit the global bar; the
        # override tenant gets its own
        assert {"task-p99-tenant-a", "task-p99-tenant-b",
                "task-p99-tenant-7"} <= names
        assert hub.slo.objective("task-p99-tenant-7").threshold_ms == 90.0
        assert hub.slo.objective("task-p99-tenant-a").threshold_ms == 250.0
        # no latency/throughput objectives without a nonzero bar
        bare = TelemetryHub(role="drv2", registry=MetricsRegistry(),
                            interval_ms=100)
        try:
            assert set(bare.slo.objectives) == {"fetch-error-ratio",
                                                "executor-liveness"}
        finally:
            bare.stop()
    finally:
        hub.stop()


# ---------------------------------------------------------------------------
# diagnosis rubric + renderers
# ---------------------------------------------------------------------------

def _breach(executor=""):
    return Breach(objective="task-p99", kind="latency", severity="page",
                  wall_ms=1000, executor=executor,
                  burn_fast=31.2, burn_fast_short=28.9)


def test_diagnosis_ranks_injected_fault_first_with_corroboration():
    spec = "stage:delay:0:delay_ms=50,stage=map_task,peer=e1"
    with faults.installed(spec) as plan:
        plan.on_stage("map_task", [], peer="e1")  # the rule actually fires
        diag = build_diagnosis(None, _breach())
        top = diag["top_cause"]
        assert top["cause"] == "injected-fault"
        assert top["executor"] == "e1"
        assert top["score"] == pytest.approx(4.0)
        assert top["corroborated"] == 0
        # when the breach itself names the same executor: corroborated
        diag2 = build_diagnosis(None, _breach(executor="e1"))
        assert diag2["top_cause"]["score"] == pytest.approx(4.5)
        assert diag2["top_cause"]["corroborated"] == 1
    text = render(diag)
    assert "injected-fault" in text and "e1" in text
    assert "task-p99" in text and "[page]" in text


def test_diagnosis_without_evidence_is_well_formed():
    diag = build_diagnosis(None, _breach())
    assert diag["kind"] == "sparkrdma_diagnosis"
    assert diag["causes"] == [] and diag["top_cause"] == {}
    assert "no candidate causes" in render(diag)


def test_obs_cli_diagnose_renders_artifacts_and_ledgers(tmp_path, capsys):
    from sparkrdma_tpu.obs.__main__ import main

    diag = build_diagnosis(None, _breach(executor="e1"))
    solo = tmp_path / "diag.json"
    solo.write_text(json.dumps(diag))
    assert main(["--diagnose", str(solo)]) == 0
    assert "SLO diagnosis" in capsys.readouterr().out
    ledger = tmp_path / "ledger.json"
    ledger.write_text(json.dumps({"slo": {
        "breach_records": [_breach(executor="e1").to_dict()],
        "diagnosis_records": [diag],
    }}))
    assert main(["--diagnose", str(ledger)]) == 0
    out = capsys.readouterr().out
    assert "task-p99" in out
    bare = tmp_path / "bare.json"
    bare.write_text(json.dumps({"workloads": []}))
    assert main(["--diagnose", str(bare)]) == 2


# ---------------------------------------------------------------------------
# e2e: deterministic chaos -> breach -> diagnosis, and the quiet control
# ---------------------------------------------------------------------------

def _run_small_job(ctx, n=400):
    data = [(f"k{i % 20}", 1) for i in range(n)]
    out = (ctx.parallelize(data, num_partitions=4)
           .reduce_by_key(lambda a, b: a + b).collect())
    assert len(out) == 20


def test_context_e2e_injected_delay_breaches_and_names_executor():
    """ISSUE 16 acceptance: a seeded stage-delay plan against exec-1
    must trip the latency objective via burn rate, and the top-ranked
    diagnosis cause must be the injected fault on that executor with a
    stage category attached."""
    from sparkrdma_tpu.engine.context import TpuContext

    conf = TpuShuffleConf({
        "tpu.shuffle.obs.telemetry.intervalMs": "40",
        "tpu.shuffle.obs.slo.taskP99Ms": "500",
        "tpu.shuffle.obs.slo.evalIntervalMs": "100",
        "tpu.shuffle.faultPlan":
            "stage:delay:0:delay_ms=1200,stage=map_task,peer=exec-1",
    })
    try:
        with TpuContext(num_executors=2, conf=conf, task_threads=2) as ctx:
            hub = ctx.driver.telemetry
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and not hub.slo.breach_total:
                _run_small_job(ctx)
                ctx.telemetry_flush()
                hub.slo.evaluate()
            summary = hub.slo.summary()
            assert summary["breach_count"] >= 1
            breaches = summary["breach_records"]
            assert any(b["objective"] == "task-p99" for b in breaches)
            diags = summary["diagnosis_records"]
            assert diags, "breach must trigger an automated diagnosis"
            top = diags[-1]["top_cause"]
            assert top["cause"] == "injected-fault"
            assert top["executor"] == "exec-1"
            assert top["category"]  # delayed stage category attached
            # the artifact rides the driver snapshot for ledgers/CI
            snap = ctx.driver.metrics_snapshot()
            assert snap["slo"]["breach_count"] >= 1
    finally:
        faults.uninstall()


def test_context_e2e_healthy_run_zero_breaches_zero_diagnoses():
    """Control group: same objectives, no fault plan -> the engine must
    stay silent (no breach, no diagnosis) over a healthy workload."""
    from sparkrdma_tpu.engine.context import TpuContext

    conf = TpuShuffleConf({
        "tpu.shuffle.obs.telemetry.intervalMs": "40",
        "tpu.shuffle.obs.slo.taskP99Ms": "500",
        "tpu.shuffle.obs.slo.evalIntervalMs": "100",
    })
    with TpuContext(num_executors=2, conf=conf, task_threads=2) as ctx:
        hub = ctx.driver.telemetry
        for _ in range(3):
            _run_small_job(ctx)
        ctx.telemetry_flush()
        hub.slo.evaluate()
        summary = hub.slo.summary()
        assert summary["breach_count"] == 0
        assert summary["diagnosis_count"] == 0


def test_cluster_e2e_exec_kill_flags_liveness_and_names_dead_executor():
    """Satellite: REAL executor loss end to end — exec:kill hard-exits
    proc-exec-1 mid-reduce; the hub's wall-clock gap accounting flags
    it, the liveness objective pages naming that executor, and the
    diagnosis carries a dead-executor cause for it."""
    from sparkrdma_tpu.engine.cluster import ClusterContext
    from sparkrdma_tpu.obs import get_registry

    conf = TpuShuffleConf({
        "tpu.shuffle.obs.telemetry.intervalMs": "50",
        "tpu.shuffle.faultPlan":
            "exec:kill:1:peer=proc-exec-1,stage=reduce_task",
    })
    g_missed0 = get_registry().gauge(
        "telemetry.missed_heartbeats", role="driver").value
    try:
        with ClusterContext(num_executors=3, conf=conf) as cc:
            hub = cc.driver.telemetry
            # The kill fires at the first reduce task, and a 6-tiny-map
            # job can finish its map phase before the first telemetry
            # poll — wait until every executor has heartbeat once so
            # the victim has a ring to go stale in.
            deadline = time.monotonic() + 15
            while (time.monotonic() < deadline
                   and len(hub.executors()) < 3):
                time.sleep(0.05)
            assert len(hub.executors()) == 3

            def mk(i):
                return lambda: iter(
                    [(f"k{j % 20}", 1) for j in range(i * 300, (i + 1) * 300)]
                )

            res = cc.run_map_reduce(
                [mk(i) for i in range(6)], num_partitions=6,
                reduce_fn=lambda it: sum(v for _, v in it),
            )
            assert sum(res) == 1800  # job survived the kill
            deadline = time.monotonic() + 15
            while (time.monotonic() < deadline
                   and "proc-exec-1" not in hub.missed_executors()):
                hub.check_missed()
                time.sleep(0.05)
            assert "proc-exec-1" in hub.missed_executors()
            assert get_registry().gauge(
                "telemetry.missed_heartbeats", role="driver"
            ).value > g_missed0
            # The page transition may already have fired from the poll
            # thread's ingest hook — assert over the cumulative record,
            # not this pass's return value.
            hub.slo.evaluate()
            assert any(
                b.objective == "executor-liveness"
                and b.executor == "proc-exec-1" and b.severity == "page"
                for b in hub.slo.breaches
            )
            diags = hub.slo.summary()["diagnosis_records"]
            assert any(
                c["cause"] == "dead-executor"
                and c["executor"] == "proc-exec-1"
                for d in diags for c in d["causes"]
            )
    finally:
        faults.uninstall()
