"""Continuous profiling plane (obs/profiler.py): hot-thread attribution
with tenant + span-category tags, heartbeat round-trip into the
driver-side merged ProfileHub, critical-path gap annotation, the
config off-switch, and the flamegraph CLI — ISSUE 15's acceptance
tests (docs/OBSERVABILITY.md "Continuous profiling")."""

import os
import subprocess
import sys
import threading
import time

from sparkrdma_tpu import tenancy
from sparkrdma_tpu.obs import (
    Heartbeater,
    MetricsRegistry,
    ProfileHub,
    SamplingProfiler,
    TelemetryHub,
    get_tracer,
    render_flamegraph_html,
)
from sparkrdma_tpu.obs.attr import classify
from sparkrdma_tpu.utils.config import TpuShuffleConf


def _hot_thread(stop: threading.Event, ready: threading.Event) -> None:
    """Busy loop under a named tenant inside a shuffle-fetch span — the
    sampler must attribute its stacks with BOTH tags."""
    with tenancy.tenant_scope("alice"):
        with get_tracer().span("shuffle.fetch.hot"):
            ready.set()
            while not stop.is_set():
                sum(i * i for i in range(200))


def _run_hot(profiler: SamplingProfiler, seconds: float = 0.2):
    stop, ready = threading.Event(), threading.Event()
    t = threading.Thread(target=_hot_thread, args=(stop, ready), daemon=True)
    profiler.start()
    try:
        t.start()
        assert ready.wait(5.0)
        time.sleep(seconds)
    finally:
        stop.set()
        t.join(5.0)
        profiler.stop()


# ---------------------------------------------------------------------------
# sampler attribution
# ---------------------------------------------------------------------------

def test_sampler_tags_hot_thread_with_tenant_and_span_category():
    reg = MetricsRegistry()
    p = SamplingProfiler(reg, role="t0", hz=200)
    _run_hot(p)
    profile = p.drain()
    assert profile is not None and profile["hz"] == 200
    want_cat = classify("shuffle.fetch.hot")
    hot = [r for r in profile["rows"]
           if r[0] == "alice" and r[1] == want_cat and "_hot_thread" in r[2]]
    assert hot, f"no tagged hot-thread rows in {profile['rows'][:5]}"
    # stacks are root-first collapsed frames: module:func;module:func
    assert ";" in hot[0][2] and ":" in hot[0][2]
    snap = reg.snapshot(prefix="profile.")
    assert snap["counters"].get("profile.samples{role=t0}", 0) > 0
    assert not p.running  # stop() joined the timer thread


def test_off_profiler_leaves_no_span_watch_cost():
    # with no sampler running, span bookkeeping must not accumulate
    from sparkrdma_tpu.obs import trace as _trace

    with get_tracer().span("shuffle.write.idle"):
        assert _trace.active_span_of_ident(threading.get_ident()) is None


# ---------------------------------------------------------------------------
# heartbeat round-trip into the merged hub
# ---------------------------------------------------------------------------

def test_profile_rows_ride_heartbeat_into_cluster_hub():
    reg = MetricsRegistry()
    hub = TelemetryHub(role="drv", interval_ms=50)
    p = SamplingProfiler(reg, role="e7", hz=200)
    hb = Heartbeater(reg, "e7", interval_ms=50, send=hub.ingest, profiler=p)
    _run_hot(p)
    hb.beat()
    hub.stop()
    assert hub.profiles.total_samples > 0
    assert "e7" in hub.profiles.executors()
    want_cat = classify("shuffle.fetch.hot")
    merged = hub.profiles.merged_rows()
    assert any(e == "e7" and t == "alice" and c == want_cat
               for e, t, c, _s, _n in merged)
    # the per-category self-time view is what critpath cross-checks
    assert hub.profiles.category_self_ms().get(want_cat, 0) > 0
    # post-mortems carry the last profile window per executor
    windows = hub.profiles.last_windows()
    assert "e7" in windows and windows["e7"]["rows"]


def test_flight_record_doc_attaches_profiles(tmp_path):
    import json

    reg = MetricsRegistry()
    hub = TelemetryHub(role="drv", interval_ms=50)
    p = SamplingProfiler(reg, role="e9", hz=200)
    hb = Heartbeater(reg, "e9", interval_ms=50, send=hub.ingest, profiler=p)
    _run_hot(p, seconds=0.1)
    hb.beat()
    out = tmp_path / "flight.json"
    hub.flight_record("profiler-test", path=str(out))
    hub.stop()
    doc = json.loads(out.read_text())
    assert "profiles" in doc and "e9" in doc["profiles"]
    assert doc["profiles"]["e9"]["rows"]


# ---------------------------------------------------------------------------
# critical-path gap annotation
# ---------------------------------------------------------------------------

def _burn_gap(seconds: float) -> int:
    # no genexpr/helper in the loop body: samples must land with THIS
    # function as the leaf frame so the gap annotation can name it
    t0 = time.perf_counter()
    x = 1
    while time.perf_counter() - t0 < seconds:
        x = (x * 1103515245 + 12345) % (1 << 31)
    return x


def test_gap_segments_name_the_sampled_busy_frame():
    from sparkrdma_tpu.obs.critpath import job_breakdown
    from sparkrdma_tpu.obs.profiler import acquire_profiler, release_profiler

    conf = TpuShuffleConf({"tpu.shuffle.obs.profile.hz": "199"})
    p = acquire_profiler(conf, role="gap-test")
    assert p is not None and p.running
    tracer = get_tracer()
    try:
        with tracer.span("job.run", job="gap-test") as job:
            with tracer.span("shuffle.write.seed"):
                time.sleep(0.02)
            _burn_gap(0.3)  # unspanned busy work = critical-path gap
        verdict = job_breakdown(job)
    finally:
        release_profiler(p)
    assert verdict.gap_frames, "no gap frames annotated"
    assert any("_burn_gap" in frame for frame in verdict.gap_frames), (
        f"busy frame not named in {sorted(verdict.gap_frames)[:5]}"
    )
    # the rendered report surfaces the dominant gap frames
    assert "gap frames" in verdict.render()


# ---------------------------------------------------------------------------
# off-switch & engine wiring
# ---------------------------------------------------------------------------

def test_off_switch_spawns_no_sampler_threads():
    from sparkrdma_tpu.engine.context import TpuContext

    conf = TpuShuffleConf({"tpu.shuffle.obs.profile.enabled": "false"})
    with TpuContext(num_executors=1, conf=conf, task_threads=1) as ctx:
        assert ctx.profiler is None
        assert not any(t.name == "sparkrdma-profiler" and t.is_alive()
                       for t in threading.enumerate())
    assert not any(t.name == "sparkrdma-profiler" and t.is_alive()
                   for t in threading.enumerate())


def test_context_profiler_is_refcounted_singleton_and_released():
    from sparkrdma_tpu.engine.context import TpuContext
    from sparkrdma_tpu.obs.profiler import get_profiler

    with TpuContext(num_executors=1, task_threads=1) as ctx:
        assert ctx.profiler is not None and ctx.profiler.running
        assert get_profiler() is ctx.profiler  # process-wide singleton
    # context stop released the last ref: the timer thread is gone
    time.sleep(0.05)
    assert not any(t.name == "sparkrdma-profiler" and t.is_alive()
                   for t in threading.enumerate())


# ---------------------------------------------------------------------------
# hub merge + flamegraph rendering
# ---------------------------------------------------------------------------

def test_hub_merges_rows_and_renders_tagged_flamegraph():
    hub = ProfileHub()
    hub.ingest("e0", {"hz": 100, "rows": [
        ["alice", "host-read", "m:a;m:b", 30],
        ["bob", "device", "m:a;m:c", 10],
    ]})
    hub.ingest("e1", {"hz": 100, "rows": [["alice", "host-read", "m:a;m:b", 5]]})
    assert hub.total_samples == 45
    assert hub.executors() == ["e0", "e1"]
    folded = hub.folded()
    assert "tenant:alice" in folded and "span:host-read" in folded
    html = hub.flamegraph_html(title="t")
    assert "tenant:alice" in html and "<html" in html.lower()
    # the standalone renderer takes (frames_root_first, count) pairs
    html2 = render_flamegraph_html([(["a", "b"], 3), (["a", "c"], 1)],
                                   title="x")
    assert "</html>" in html2


def test_cli_demo_writes_folded_and_flamegraph(tmp_path):
    html = tmp_path / "flame.html"
    folded = tmp_path / "stacks.folded"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "sparkrdma_tpu.obs", "--demo",
         "--flamegraph", str(html), "--folded", str(folded)],
        capture_output=True, text=True, timeout=240, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    text = folded.read_text()
    lines = [ln for ln in text.splitlines() if ln.strip()]
    assert lines and all(ln.rsplit(" ", 1)[1].isdigit() for ln in lines)
    assert "tenant:" in text and "span:" in text
    page = html.read_text()
    assert "</html>" in page and "tenant:" in page
