"""Unit coverage for the resilience layer (docs/RESILIENCE.md).

RetryPolicy determinism, the CircuitBreaker state machine, the checksum
utility, the RPC checksum wire extension (including legacy frames), the
fault-plan spec grammar, and the error taxonomy in shuffle/errors.py.
"""

import zlib

import pytest

from sparkrdma_tpu.locations import (
    BlockLocation,
    PartitionLocation,
    ShuffleManagerId,
)
from sparkrdma_tpu.resilience import (
    CircuitBreaker,
    CircuitOpenError,
    RetryPolicy,
    SourceHealthRegistry,
)
from sparkrdma_tpu.rpc import PublishPartitionLocationsMsg, RpcMsg
from sparkrdma_tpu.shuffle.errors import (
    ChecksumError,
    FetchFailedError,
    MetadataFetchFailedError,
    ShuffleError,
)
from sparkrdma_tpu.testing.faults import FaultPlan, FaultRule, InjectedFault
from sparkrdma_tpu.utils import checksum
from sparkrdma_tpu.utils.config import TpuShuffleConf


# ----------------------------------------------------------------------
# RetryPolicy
# ----------------------------------------------------------------------
def test_retry_policy_from_conf_and_allows():
    conf = TpuShuffleConf(
        {
            "tpu.shuffle.resilience.maxFetchAttempts": "3",
            "tpu.shuffle.resilience.retryBackoffMs": "10",
            "tpu.shuffle.resilience.retryBackoffMaxMs": "40",
            "tpu.shuffle.resilience.fetchDeadlineMs": "5000",
        }
    )
    p = RetryPolicy.from_conf(conf)
    assert p.max_attempts == 3
    assert p.allows(1) and p.allows(2)
    assert not p.allows(3)
    assert p.deadline_s() == pytest.approx(5.0)


def test_retry_policy_backoff_deterministic_and_bounded():
    p = RetryPolicy(max_attempts=5, backoff_ms=50, backoff_max_ms=400)
    # same (attempt, keys) -> same jittered delay, run to run
    a = p.backoff_s(1, 7, "exec-1", 3)
    b = p.backoff_s(1, 7, "exec-1", 3)
    assert a == b
    # different keys de-synchronize retries
    assert p.backoff_s(1, 7, "exec-2", 3) != a
    # exponential growth capped at backoff_max_ms; jitter keeps every
    # delay within [base/2, base]
    for attempt in range(5):
        base = min(50 * 2**attempt, 400) / 1000.0
        d = p.backoff_s(attempt, "k")
        assert base / 2 <= d <= base


def test_retry_policy_no_deadline_is_infinite():
    assert RetryPolicy().deadline_s() == float("inf")


# ----------------------------------------------------------------------
# CircuitBreaker
# ----------------------------------------------------------------------
def test_circuit_breaker_state_machine():
    t = [0.0]
    cb = CircuitBreaker(failure_threshold=3, open_ms=1000, clock=lambda: t[0])
    assert cb.state == "closed" and cb.allow()
    cb.record_failure()
    cb.record_failure()
    assert cb.state == "closed"
    assert cb.record_failure() is True  # third failure opens
    assert cb.state == "open" and not cb.allow()
    # a success while open/half-open doesn't reset the clock backwards
    t[0] = 0.5
    assert not cb.allow()
    t[0] = 1.1  # past open_ms: half-open admits exactly one probe
    assert cb.allow()
    assert cb.state == "half_open"
    assert not cb.allow()  # second caller blocked while the probe flies
    cb.record_success()
    assert cb.state == "closed" and cb.allow()


def test_circuit_breaker_half_open_failure_reopens():
    t = [0.0]
    cb = CircuitBreaker(failure_threshold=1, open_ms=1000, clock=lambda: t[0])
    cb.record_failure()
    assert cb.state == "open"
    t[0] = 1.5
    assert cb.allow()  # the half-open probe
    cb.record_failure()
    assert cb.state == "open"
    assert not cb.allow()
    # and it stays open for a fresh full window
    t[0] = 2.0
    assert not cb.allow()


def test_circuit_breaker_success_resets_failure_streak():
    cb = CircuitBreaker(failure_threshold=2, open_ms=1000)
    cb.record_failure()
    cb.record_success()
    cb.record_failure()
    assert cb.state == "closed"  # streak broken by the success


def test_source_health_registry_per_peer():
    conf = TpuShuffleConf(
        {"tpu.shuffle.resilience.circuitFailureThreshold": "1"}
    )
    reg = SourceHealthRegistry(conf, role="t")
    reg.record_failure("exec-bad")
    assert not reg.allow("exec-bad")
    assert reg.allow("exec-good")  # breakers are per-peer
    assert reg.states()["exec-bad"] == "open"


# ----------------------------------------------------------------------
# checksum utility
# ----------------------------------------------------------------------
def test_checksum_roundtrip_and_mismatch():
    data = b"the quick brown fox"
    algo, crc = checksum.compute(data)
    assert algo != checksum.ALGO_NONE
    assert checksum.verify(data, crc, algo)
    assert not checksum.verify(data + b"!", crc, algo)
    assert not checksum.verify(b"", crc, algo)


def test_checksum_none_and_unknown_algos_pass():
    data = b"xyz"
    assert checksum.verify(data, 0, checksum.ALGO_NONE)
    # unverifiable (unknown algo tag) must PASS, not fail the fetch
    assert checksum.verify(data, 123, 250)


def test_checksum_crc32_matches_zlib():
    data = b"payload" * 100
    _, crc = checksum.compute(data, algo=checksum.ALGO_CRC32)
    assert crc == zlib.crc32(data) & 0xFFFFFFFF
    assert checksum.verify(memoryview(data), crc, checksum.ALGO_CRC32)


# ----------------------------------------------------------------------
# RPC checksum wire extension
# ----------------------------------------------------------------------
def _mk_loc(pid, length, mkey, ck=0, algo=0):
    return PartitionLocation(
        ShuffleManagerId("host", 1234, f"exec-{mkey}"),
        pid,
        BlockLocation(0, length, mkey, checksum=ck, checksum_algo=algo),
    )


def test_publish_msg_checksum_extension_roundtrip():
    locs = [
        _mk_loc(0, 100, 7, ck=0xDEADBEEF, algo=checksum.ALGO_CRC32),
        _mk_loc(1, 200, 8, ck=0x12345678, algo=checksum.ALGO_CRC32),
    ]
    msg = PublishPartitionLocationsMsg(5, -1, locs, trace_id=0xABC)
    segments = msg.to_segments(4096)
    out = [RpcMsg.parse_segment(seg) for seg in segments]
    got = [loc for m in out for loc in m.locations]
    assert [
        (loc.partition_id, loc.block.checksum, loc.block.checksum_algo) for loc in got
    ] == [
        (0, 0xDEADBEEF, checksum.ALGO_CRC32),
        (1, 0x12345678, checksum.ALGO_CRC32),
    ]
    # trace id still parses alongside the checksum extension
    assert all(m.shuffle_id == 5 for m in out)
    assert all(m.trace_id == 0xABC for m in out)


def test_publish_msg_without_checksums_is_legacy_compatible():
    """No checksum -> no extension bytes: a legacy/foreign parser that
    knows nothing of the extension sees the exact old frame layout, and
    our parser reads such frames with zeroed checksum fields."""
    locs = [_mk_loc(0, 64, 3), _mk_loc(1, 64, 4)]
    msg = PublishPartitionLocationsMsg(2, -1, locs)
    baseline = PublishPartitionLocationsMsg(
        2,
        -1,
        [
            PartitionLocation(
                loc.manager_id, loc.partition_id,
                BlockLocation(loc.block.address, loc.block.length, loc.block.mkey),
            )
            for loc in locs
        ],
    )
    assert msg.to_segments(4096) == baseline.to_segments(4096)
    (seg,) = msg.to_segments(4096)
    m = RpcMsg.parse_segment(seg)
    assert [loc.block.checksum_algo for loc in m.locations] == [0, 0]
    assert m.shuffle_id == 2 and m.partition_id == -1


def test_publish_msg_checksum_survives_segmentation():
    """Checksums stay attached to THEIR location across segment splits."""
    locs = [
        _mk_loc(i, 10 + i, 100 + i, ck=i * 7 + 1, algo=checksum.ALGO_CRC32)
        for i in range(40)
    ]
    msg = PublishPartitionLocationsMsg(9, -1, locs)
    # small segment budget forces multiple segments
    segments = msg.to_segments(256)
    assert len(segments) > 1
    got = []
    for seg in segments:
        got.extend(RpcMsg.parse_segment(seg).locations)
    assert len(got) == 40
    for i, loc in enumerate(sorted(got, key=lambda x: x.partition_id)):
        assert loc.block.checksum == i * 7 + 1


# ----------------------------------------------------------------------
# errors taxonomy
# ----------------------------------------------------------------------
def test_error_taxonomy():
    mid = ShuffleManagerId("h", 1, "e")
    f = FetchFailedError(mid, 1, 2, 3, "boom")
    assert isinstance(f, ShuffleError)
    assert f.manager_id is mid and f.partition_id == 3
    assert "boom" in str(f)

    m = MetadataFetchFailedError(4, 5, "nope")
    assert isinstance(m, ShuffleError)
    assert m.shuffle_id == 4 and m.partition_id == 5

    c = ChecksumError(6, 7, "mismatch")
    assert isinstance(c, IOError)
    assert not isinstance(c, ShuffleError)  # retryable, not terminal
    assert c.shuffle_id == 6 and c.partition_id == 7

    o = CircuitOpenError("open")
    assert isinstance(o, IOError)
    assert not isinstance(o, ShuffleError)


# ----------------------------------------------------------------------
# fault-plan grammar
# ----------------------------------------------------------------------
def test_fault_rule_parse_full_grammar():
    r = FaultRule.parse("read:fail:3:after=2,delay_ms=10,peer=exec-1")
    assert (r.op, r.kind, r.count, r.after, r.delay_ms, r.peer) == (
        "read", "fail", 3, 2, 10, "exec-1"
    )
    with pytest.raises(ValueError):
        FaultRule.parse("bogus:fail")
    with pytest.raises(ValueError):
        FaultRule.parse("read:bogus")
    with pytest.raises(ValueError):
        FaultRule.parse("read")


def test_fault_plan_counting_and_after():
    plan = FaultPlan.parse("read:fail:2:after=1")

    class _Chan:
        peer_desc = "exec-x"

    class _L:
        def __init__(self):
            self.failures = []

        def on_success(self, p):
            pass

        def on_failure(self, e):
            self.failures.append(e)

    listeners = [_L() for _ in range(4)]
    handled = []
    for lst in listeners:
        _, h = plan.on_read(_Chan(), lst, [bytearray(4)], [(0, 0, 4)])
        handled.append(h)
    # first call skipped (after=1), next two fire, budget then exhausted
    assert handled == [False, True, True, False]
    assert plan.injected_count("read", "fail") == 2
    assert plan.total_injected == 2
    assert isinstance(listeners[1].failures[0], InjectedFault)


def test_fault_plan_corrupt_flips_one_byte_deterministically():
    plan_a = FaultPlan.parse("read:corrupt:1", seed=42)
    plan_b = FaultPlan.parse("read:corrupt:1", seed=42)

    class _Chan:
        peer_desc = "p"

    class _L:
        def on_success(self, p):
            pass

        def on_failure(self, e):
            raise AssertionError(e)

    outs = []
    for plan in (plan_a, plan_b):
        buf = bytearray(b"\x00" * 64)
        wrapped, handled = plan.on_read(_Chan(), _L(), [memoryview(buf)], [])
        assert not handled
        wrapped.on_success(None)  # corruption happens at completion
        outs.append(bytes(buf))
    assert outs[0] == outs[1]  # same seed -> same flipped byte
    assert sum(b != 0 for b in outs[0]) == 1


def test_fault_plan_peer_filter():
    plan = FaultPlan.parse("read:fail:0:peer=exec-7")

    class _Chan:
        def __init__(self, d):
            self.peer_desc = d

    class _L:
        def on_success(self, p):
            pass

        def on_failure(self, e):
            pass

    _, h1 = plan.on_read(_Chan("to exec-7 data"), _L(), [], [])
    _, h2 = plan.on_read(_Chan("to exec-9 data"), _L(), [], [])
    assert h1 and not h2


def test_fault_plan_rpc_seam():
    plan = FaultPlan.parse("rpc:drop:1")
    payload, handled = plan.on_rpc("peer", b"abc")
    assert handled
    payload, handled = plan.on_rpc("peer", b"abc")
    assert not handled and payload == b"abc"
