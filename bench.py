"""Benchmark: the framework's measured planes, one JSON line.

The reference's only published number is HiBench TeraSort 1.41x over
stock Spark sort shuffle on 100 GbE RoCE — won by replacing the
*transport* under Spark's unchanged sort machinery
(/root/reference/README.md:7-19, BASELINE.md). This bench measures the
same planes of this framework on one chip + one host:

- ``value`` / north star: **shuffle-read GB/s per chip** through the
  native one-sided READ plane (same-host pread fast path — the
  reference hot-path shape: 8 MiB read groups from registered memory,
  RdmaChannel.java:360-393 + RdmaMappedFile.java:135-209).
  ``vs_baseline`` divides by 12.5 GB/s, the 100 GbE wire-rate
  operating point the reference tuned against (BASELINE.md).
  ``pread_roofline_gbps`` is the MACHINE's limit for this path —
  raw single-core page-cache pread into the same rotating
  destination set, measured in-process — so the headline is
  interpretable: on this 1-core box the transport saturates it
  (~4 GB/s ≈ 100% of roofline; a naive single-dst probe reads ~70%
  high because the destination stays cache-resident).
- ``native_read_streamed_gbps``: the same READ path when the region is
  anonymous (no file backing), so every byte moves through the socket
  streaming plane. ``native_read_streamed_sendfile_gbps`` is the
  file-backed variant served by kernel ``sendfile`` (forced on for the
  bench: loopback peers normally keep the userspace send, which
  measures ~18% faster on this rig; sendfile is for real NICs).
- **fetch-to-CONSUMED planes** — where beating the copy roofline is
  physically possible: ``native_read_samehost_consumed_pread_gbps``
  (pread into a buffer, then one consume pass: 2 passes/byte) vs
  ``native_read_mapped_consumed_gbps`` (mapped zero-copy delivery with
  MAP_POPULATE prefaulting: the consume pass IS the first touch —
  1 pass/byte), both against ``consume_roofline_gbps`` (delivery
  assumed free). ``native_read_samehost_consumed_gbps`` reports the
  DEFAULT consume path — the mapped plane (conf mappedFetch=true on
  capable channels). Measured: mapped ≈ 1.4x the pread path at ≈ 90%
  of the roofline; ``ab_consume_mapped`` pins the delta with
  interleaved same-run pairs.
- ``pread_roofline_2thr_gbps``: 2-way threaded pread of the same
  volume. On this nproc=1 box it still measures ~1.4x one thread
  (kernel-side parallelism exists), but the gain does NOT survive the
  full stack (per-block control overheads serialize on the loop
  threads) — recorded so the striping story is numbers, not lore.
- ``device_sort_gbps`` + ``terasort_speedup_vs_host_sort``: the jitted
  TeraSort step, whose hot path is ``ops/sort.device_sort`` —
  ``lax.sort``, the measured optimum for this chip (evidence:
  benchmarks/sort_study.py, DESIGN.md §6; rounds 1-3 assumed a faster
  decomposition existed, round 4 measured that none does). Output is
  verified against the host sort in-loop.
- ``flash_attn_tflops``: the Pallas flash kernel, causal bf16
  B4 S2048 H8 D128 with measured 1024x1024 blocks, against XLA's
  materialized-scores attention timed identically in the same process
  (``flash_vs_xla_dense``). ``flash_train_tflops`` adds the custom
  VJP (blockwise dq / dkdv kernels): one full forward+backward per
  step, so long-context training runs at flash memory cost.
- ``ab_samehost_fileworkers`` / ``ab_streamed_connections``:
  interleaved SAME-RUN striped-vs-unstriped A/B pairs (fileWorkers
  1 vs N on the pread plane; 1 vs M data connections on the streamed
  plane) — per-pair ratios are immune to the run-to-run rig drift that
  made cross-round striping comparisons lore.
- ``flash_attn_mfu`` / ``flash_train_mfu``: the measured TFLOPs over
  the chip's dense bf16 peak (small public-spec table keyed on
  ``device_kind``; null off-TPU rather than a made-up peak).
- ``exchange_loopback_gbps``: the resident ExchangeProgram executable
  on the single-device mesh. Labeled loopback: at E=1 the collective
  degenerates to an on-device pass, so this bounds program overhead;
  multi-device exchange is validated functionally by
  ``__graft_entry__.dryrun_multichip`` (real chips unavailable here).

Deliberately ABSENT: host<->HBM staging bandwidth. On this rig the TPU
sits behind the axon network tunnel — ``jax.device_put`` of 128 MiB
swings 0.13-1.4 GB/s and a 4 MiB readback takes ~30 s — so a staging
number would measure the tunnel, not the framework. Device compute is
timed with the only methodology that survives the tunnel: K
data-dependent steps chained inside ONE jitted program, differenced
against a shorter chain, scalar readback (``block_until_ready``
returns early on this platform).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

from __future__ import annotations

import json
import threading
import time
from functools import partial

import numpy as np

WIRE_RATE_GBPS = 12.5  # 100 GbE operating point (BASELINE.md)
N_KEYS = 1 << 25       # 32M uint32 keys = 128 MiB
READ_BLOCK = 8 << 20   # reference shuffleReadBlockSize default
READ_REGION = 64 << 20
READ_TOTAL = 1 << 30


# ---------------------------------------------------------------------------
# host plane: native one-sided READ bandwidth
# ---------------------------------------------------------------------------

def bench_native_reads() -> dict:
    from sparkrdma_tpu.memory.buffer import TpuBuffer
    from sparkrdma_tpu.transport import FnListener
    from sparkrdma_tpu.transport.native_node import NativeTpuNode
    from sparkrdma_tpu.utils.config import TpuShuffleConf

    conf = TpuShuffleConf()
    srv = NativeTpuNode(conf, "127.0.0.1", False, "bench-srv")
    cli = NativeTpuNode(conf, "127.0.0.1", True, "bench-cli")
    out = {}
    try:
        rng = np.random.default_rng(7)
        ch = cli.get_channel("127.0.0.1", srv.port)
        n_blocks = READ_REGION // READ_BLOCK
        rounds = READ_TOTAL // READ_REGION
        dsts = [memoryview(bytearray(READ_BLOCK)) for _ in range(n_blocks)]

        def one_round(mkey, label, c=None):
            c = c or ch
            evs = []
            errs = []
            for i in range(n_blocks):
                ev = threading.Event()

                def fail(e, ev=ev):
                    errs.append(e)
                    ev.set()

                c.read_in_queue(
                    FnListener(lambda _, ev=ev: ev.set(), fail),
                    [dsts[i]], [(mkey, i * READ_BLOCK, READ_BLOCK)],
                )
                evs.append(ev)
            for ev in evs:
                assert ev.wait(120), f"{label} read timed out"
            if errs:
                raise SystemExit(f"BENCH FAILED: {label} READ error: {errs[0]}")

        def pull(mkey, label, channel=None, consume=False):
            c = channel or ch
            one_round(mkey, label, c)  # warm: connection, fd + page cache
            sink = 0
            t0 = time.perf_counter()
            for _ in range(rounds):
                one_round(mkey, label, c)
                if consume:
                    for d in dsts:
                        sink += int(
                            np.add.reduce(
                                np.frombuffer(d, np.uint8), dtype=np.int64
                            )
                        )
            gbps = READ_TOTAL / (time.perf_counter() - t0) / 1e9
            return (gbps, sink) if consume else gbps

        def pull_mapped_consumed(mkey, channel):
            """Mapped delivery + one consume pass per block: the
            fetch-to-consumed number for the zero-copy plane. The
            consume (a full-speed sum over the mapping) is the FIRST
            touch of those page-cache pages in userspace — the pread
            plane pays the same pass PLUS its copy first."""
            def one_mapped_round():
                evs, deliveries, errs = [], [None] * n_blocks, []
                for i in range(n_blocks):
                    ev = threading.Event()

                    def ok(d, i=i, ev=ev):
                        deliveries[i] = d
                        ev.set()

                    def fail(e, ev=ev):
                        errs.append(e)
                        ev.set()

                    channel.read_mapped_in_queue(
                        FnListener(ok, fail),
                        [(mkey, i * READ_BLOCK, READ_BLOCK)],
                    )
                    evs.append(ev)
                sink = 0
                for i, ev in enumerate(evs):
                    assert ev.wait(120), "mapped read timed out"
                    if errs:
                        raise SystemExit(f"BENCH FAILED: mapped READ: {errs[0]}")
                    d = deliveries[i]
                    sink += int(
                        np.add.reduce(
                            np.frombuffer(d.views[0], np.uint8), dtype=np.int64
                        )
                    )
                    d.release()
                return sink

            one_mapped_round()  # warm
            sink = 0
            t0 = time.perf_counter()
            for _ in range(rounds):
                sink += one_mapped_round()
            return READ_TOTAL / (time.perf_counter() - t0) / 1e9, sink

        # machine roofline for the fast path: raw page-cache pread into
        # the SAME rotating destination set (cache-honest: a single
        # reused dst stays L3-resident and reads ~70% too fast)
        import os
        import tempfile

        with tempfile.NamedTemporaryFile(dir="/dev/shm") as f:
            f.write(rng.integers(0, 256, READ_REGION, dtype=np.uint8).tobytes())
            f.flush()
            rfd = f.fileno()
            for i in range(n_blocks):
                os.preadv(rfd, [dsts[i]], i * READ_BLOCK)
            t0 = time.perf_counter()
            moved = 0
            for _ in range(rounds):
                for i in range(n_blocks):
                    moved += os.preadv(rfd, [dsts[i]], i * READ_BLOCK)
            out["pread_roofline_gbps"] = round(
                moved / (time.perf_counter() - t0) / 1e9, 3
            )

            # striping non-lever evidence: the reference stripes READs
            # over multiple QPs because NIC/core parallelism exists;
            # this box has ONE core, so 2-way threaded pread of the
            # same volume cannot beat the single-thread roofline —
            # measured here so the design choice (kill copies, don't
            # stripe) is a number, not an assertion
            from concurrent.futures import ThreadPoolExecutor

            def half(lo, hi):
                m = 0
                for _ in range(rounds):
                    for i in range(lo, hi):
                        m += os.preadv(rfd, [dsts[i]], i * READ_BLOCK)
                return m

            with ThreadPoolExecutor(2) as pool:
                t0 = time.perf_counter()
                futs = [
                    pool.submit(half, 0, n_blocks // 2),
                    pool.submit(half, n_blocks // 2, n_blocks),
                ]
                moved = sum(f.result() for f in futs)
                out["pread_roofline_2thr_gbps"] = round(
                    moved / (time.perf_counter() - t0) / 1e9, 3
                )

        # same-host fast path: shm-backed registered slab (pread plane)
        buf = TpuBuffer(srv.pd, READ_REGION, register=True)
        src = rng.integers(0, 256, size=READ_REGION, dtype=np.uint8)
        np.frombuffer(buf.view, dtype=np.uint8)[:] = src
        gbps = pull(buf.mkey, "samehost")
        if not np.array_equal(np.frombuffer(dsts[1], np.uint8),
                              src[READ_BLOCK: 2 * READ_BLOCK]):
            raise SystemExit("BENCH FAILED: samehost READ bytes differ")
        fast, _ = cli.read_path_stats()
        if fast == 0:
            raise SystemExit("BENCH FAILED: samehost reads never took fast path")
        out["native_read_samehost_gbps"] = round(gbps, 3)

        # fetch-to-CONSUMED comparison on the same region: the pread
        # plane copies into a buffer the consumer then reads (2 passes
        # per byte); mapped delivery hands the consumer the page-cache
        # pages themselves (1 pass). Same consume (full-speed uint8
        # sum) both sides, so the delta is pure delivery cost — this is
        # where "beat your own roofline" is physically possible on a
        # 1-core box: not by copying faster, but by not copying.
        want_sum = int(np.add.reduce(src, dtype=np.int64)) * rounds
        gbps_c, sink = pull(buf.mkey, "samehost+consume", consume=True)
        if sink != want_sum:
            raise SystemExit("BENCH FAILED: consumed pread sum differs")
        out["native_read_samehost_consumed_pread_gbps"] = round(gbps_c, 3)
        gbps_m, sink_m = pull_mapped_consumed(buf.mkey, ch)
        if sink_m != want_sum:
            raise SystemExit("BENCH FAILED: consumed mapped sum differs")
        out["native_read_mapped_consumed_gbps"] = round(gbps_m, 3)
        # the headline consumed number reports the DEFAULT consume path:
        # mapped zero-copy delivery (conf mappedFetch=true, the record
        # and device fetchers both select it on capable channels) with
        # MAP_POPULATE prefaulting on the file worker. One pass per
        # byte instead of copy+pass — the only shape that can approach
        # the consume roofline on a 1-core box. The pread plane's
        # number stays above as *_consumed_pread_gbps.
        out["native_read_samehost_consumed_gbps"] = round(gbps_m, 3)
        # this comparison's machine limit: ONE touch pass per byte over
        # the same rotating set (delivery assumed free)
        for d in dsts:
            np.add.reduce(np.frombuffer(d, np.uint8), dtype=np.int64)
        t0 = time.perf_counter()
        moved = 0
        for _ in range(rounds):
            for d in dsts:
                np.add.reduce(np.frombuffer(d, np.uint8), dtype=np.int64)
                moved += READ_BLOCK
        out["consume_roofline_gbps"] = round(
            moved / (time.perf_counter() - t0) / 1e9, 3
        )

        # streamed plane with the SAME file-backed region: a client
        # with fileFastPath=false simulates a remote peer, the server
        # serves via sendfile (kernel zero-copy; one userspace copy per
        # byte total vs the plain socket plane's two)
        # second server with forceSendfile (loopback peers would
        # otherwise get the faster-on-this-rig userspace send)
        conf_sf = TpuShuffleConf({"tpu.shuffle.fileFastPath": "false"})
        srv_sf = NativeTpuNode(
            TpuShuffleConf({"tpu.shuffle.forceSendfile": "true"}),
            "127.0.0.1", False, "bench-srv-sf",
        )
        cli_sf = NativeTpuNode(conf_sf, "127.0.0.1", True, "bench-cli-sf")
        try:
            buf_sf = TpuBuffer(srv_sf.pd, READ_REGION, register=True)
            np.frombuffer(buf_sf.view, dtype=np.uint8)[:] = src
            ch_sf = cli_sf.get_channel("127.0.0.1", srv_sf.port)
            gbps = pull(buf_sf.mkey, "streamed-sendfile", channel=ch_sf)
            if not np.array_equal(np.frombuffer(dsts[2], np.uint8),
                                  src[2 * READ_BLOCK: 3 * READ_BLOCK]):
                raise SystemExit("BENCH FAILED: sendfile READ bytes differ")
            f_sf, s_sf = cli_sf.read_path_stats()
            if f_sf != 0 or s_sf == 0:
                raise SystemExit("BENCH FAILED: sendfile pull not streamed")
            out["native_read_streamed_sendfile_gbps"] = round(gbps, 3)
        finally:
            cli_sf.stop()
            srv_sf.stop()
        buf.free()

        # streamed plane: anonymous region -> socket streaming path
        anon = rng.integers(0, 256, size=READ_REGION, dtype=np.uint8)
        mkey2 = srv.pd.register(memoryview(anon.data))
        gbps = pull(mkey2, "streamed")
        if not np.array_equal(np.frombuffer(dsts[1], np.uint8),
                              anon[READ_BLOCK: 2 * READ_BLOCK]):
            raise SystemExit("BENCH FAILED: streamed READ bytes differ")
        out["native_read_streamed_gbps"] = round(gbps, 3)

        # this plane's machine limit: raw single-core loopback socket
        # (8 MiB sends, rotating destination set, same rig)
        out["socket_roofline_gbps"] = _socket_roofline()
        # ...and the sendfile plane's: kernel-side file->socket moves,
        # userspace only on the receive side
        out["sendfile_roofline_gbps"] = _sendfile_roofline()
    finally:
        cli.stop()
        srv.stop()
    return out


def bench_consume_pipelined_ab() -> dict:
    """Interleaved serial-vs-pipelined consume A/B pairs, SAME run.

    BENCH_r05 pinned the reduce-side loss: same-host native READ
    sustains ~4 GB/s raw but only ~1.5 GB/s fetch-to-CONSUMED against a
    ~2.4 GB/s consume roofline — the READ wait and the consume pass ran
    strictly in sequence. The reduce pipeline's lever (DESIGN.md §16)
    is to keep the next group's READs in flight under the current
    group's consume; this A/B isolates exactly that on the same-host
    pread plane. The A side is today's serial loop (the
    ``native_read_samehost_consumed_gbps`` shape: read a region, then
    sum it). The B side double-buffers two destination sets: round
    k+1's preads (C++ file workers — the GIL is released) land while
    round k is consumed (``np.add.reduce`` — also GIL-free), same total
    volume and the same consume pass per byte. Same interleaved-pair
    methodology as :func:`bench_striping_ab`, so per-pair ratios are
    drift-immune; both sides verify the summed payload."""
    from sparkrdma_tpu.memory.buffer import TpuBuffer
    from sparkrdma_tpu.transport import FnListener
    from sparkrdma_tpu.transport.native_node import NativeTpuNode
    from sparkrdma_tpu.utils.config import TpuShuffleConf

    out = {}
    rng = np.random.default_rng(13)
    srv = NativeTpuNode(TpuShuffleConf(), "127.0.0.1", False, "cab-srv")
    cli = NativeTpuNode(TpuShuffleConf(), "127.0.0.1", True, "cab-cli")
    n_blocks = READ_REGION // READ_BLOCK
    N_PAIRS = 3
    ROUNDS_PER_SIDE = 4
    dsts_a = [memoryview(bytearray(READ_BLOCK)) for _ in range(n_blocks)]
    dsts_b = [memoryview(bytearray(READ_BLOCK)) for _ in range(n_blocks)]
    try:
        ch = cli.get_channel("127.0.0.1", srv.port)
        src = rng.integers(0, 256, size=READ_REGION, dtype=np.uint8)
        buf = TpuBuffer(srv.pd, READ_REGION, register=True)
        np.frombuffer(buf.view, dtype=np.uint8)[:] = src
        want_round = int(np.add.reduce(src, dtype=np.int64))

        def issue(dsts):
            evs, errs = [], []
            for i in range(n_blocks):
                ev = threading.Event()

                def fail(e, ev=ev):
                    errs.append(e)
                    ev.set()

                ch.read_in_queue(
                    FnListener(lambda _, ev=ev: ev.set(), fail),
                    [dsts[i]], [(buf.mkey, i * READ_BLOCK, READ_BLOCK)],
                )
                evs.append(ev)
            return evs, errs

        def wait(evs, errs):
            for ev in evs:
                assert ev.wait(120), "consume A/B read timed out"
            if errs:
                raise SystemExit(
                    f"BENCH FAILED: consume A/B READ error: {errs[0]}"
                )

        def consume(dsts):
            s = 0
            for d in dsts:
                s += int(
                    np.add.reduce(np.frombuffer(d, np.uint8), dtype=np.int64)
                )
            return s

        def serial_side():
            sink = 0
            t0 = time.perf_counter()
            for _ in range(ROUNDS_PER_SIDE):
                wait(*issue(dsts_a))
                sink += consume(dsts_a)
            dt = time.perf_counter() - t0
            return ROUNDS_PER_SIDE * READ_REGION / dt / 1e9, sink

        def pipelined_side():
            sink = 0
            t0 = time.perf_counter()
            pend = issue(dsts_a)
            cur, nxt = dsts_a, dsts_b
            for r in range(ROUNDS_PER_SIDE):
                wait(*pend)
                if r + 1 < ROUNDS_PER_SIDE:
                    pend = issue(nxt)
                sink += consume(cur)
                cur, nxt = nxt, cur
            dt = time.perf_counter() - t0
            return ROUNDS_PER_SIDE * READ_REGION / dt / 1e9, sink

        # warm: connection, fd + page cache, BOTH destination sets
        # faulted in (the B side must not pay first-touch the A side
        # already paid)
        wait(*issue(dsts_a))
        wait(*issue(dsts_b))
        fast, _ = cli.read_path_stats()
        if fast == 0:
            raise SystemExit(
                "BENCH FAILED: consume A/B never took the fast path"
            )
        pairs = []
        for _ in range(N_PAIRS):
            a, sink_a = serial_side()
            b, sink_b = pipelined_side()
            if (sink_a != want_round * ROUNDS_PER_SIDE
                    or sink_b != want_round * ROUNDS_PER_SIDE):
                raise SystemExit("BENCH FAILED: consume A/B sums differ")
            pairs.append(
                {"serial_gbps": round(a, 3), "pipelined_gbps": round(b, 3)}
            )
        med_a = float(np.median([p["serial_gbps"] for p in pairs]))
        med_b = float(np.median([p["pipelined_gbps"] for p in pairs]))
        out["ab_consume_pipelined"] = {
            "pairs": pairs,
            "native_read_samehost_consumed_gbps": round(med_a, 3),
            "native_read_samehost_consumed_pipelined_gbps": round(med_b, 3),
            "pipelined_speedup": round(med_b / med_a, 3) if med_a else None,
        }
        buf.free()
    finally:
        cli.stop()
        srv.stop()
    return out


def bench_consume_mapped_ab() -> dict:
    """Interleaved pread-vs-mapped consume A/B pairs, SAME run.

    The consume-path ceiling satellite: the pread plane pays two passes
    per byte (page cache -> destination buffer, then the consumer's
    sum) and is structurally capped below the one-pass consume
    roofline; mapped delivery hands the consumer the MAP_POPULATE-
    prefaulted page-cache pages themselves. This A/B pins the delta
    with drift-immune interleaved pairs: the A side is the pread
    consume loop, the B side the mapped consume loop, same volume, same
    full-speed uint8 sum per byte, sums verified both sides. B is the
    DEFAULT fetch shape (conf mappedFetch=true on capable channels) —
    the top-level ``native_read_samehost_consumed_gbps`` reports it."""
    from sparkrdma_tpu.memory.buffer import TpuBuffer
    from sparkrdma_tpu.transport import FnListener
    from sparkrdma_tpu.transport.native_node import NativeTpuNode
    from sparkrdma_tpu.utils.config import TpuShuffleConf

    out = {}
    rng = np.random.default_rng(17)
    srv = NativeTpuNode(TpuShuffleConf(), "127.0.0.1", False, "cmab-srv")
    cli = NativeTpuNode(TpuShuffleConf(), "127.0.0.1", True, "cmab-cli")
    n_blocks = READ_REGION // READ_BLOCK
    N_PAIRS = 3
    ROUNDS_PER_SIDE = 4
    dsts = [memoryview(bytearray(READ_BLOCK)) for _ in range(n_blocks)]
    try:
        ch = cli.get_channel("127.0.0.1", srv.port)
        src = rng.integers(0, 256, size=READ_REGION, dtype=np.uint8)
        buf = TpuBuffer(srv.pd, READ_REGION, register=True)
        np.frombuffer(buf.view, dtype=np.uint8)[:] = src
        want_round = int(np.add.reduce(src, dtype=np.int64))

        def pread_round():
            evs, errs = [], []
            for i in range(n_blocks):
                ev = threading.Event()

                def fail(e, ev=ev):
                    errs.append(e)
                    ev.set()

                ch.read_in_queue(
                    FnListener(lambda _, ev=ev: ev.set(), fail),
                    [dsts[i]], [(buf.mkey, i * READ_BLOCK, READ_BLOCK)],
                )
                evs.append(ev)
            for ev in evs:
                assert ev.wait(120), "mapped A/B pread timed out"
            if errs:
                raise SystemExit(
                    f"BENCH FAILED: mapped A/B READ error: {errs[0]}"
                )
            s = 0
            for d in dsts:
                s += int(
                    np.add.reduce(np.frombuffer(d, np.uint8), dtype=np.int64)
                )
            return s

        def mapped_round():
            evs, deliveries, errs = [], [None] * n_blocks, []
            for i in range(n_blocks):
                ev = threading.Event()

                def ok(d, i=i, ev=ev):
                    deliveries[i] = d
                    ev.set()

                def fail(e, ev=ev):
                    errs.append(e)
                    ev.set()

                ch.read_mapped_in_queue(
                    FnListener(ok, fail),
                    [(buf.mkey, i * READ_BLOCK, READ_BLOCK)],
                )
                evs.append(ev)
            s = 0
            for i, ev in enumerate(evs):
                assert ev.wait(120), "mapped A/B mapped read timed out"
                if errs:
                    raise SystemExit(
                        f"BENCH FAILED: mapped A/B mapped READ: {errs[0]}"
                    )
                d = deliveries[i]
                s += int(
                    np.add.reduce(
                        np.frombuffer(d.views[0], np.uint8), dtype=np.int64
                    )
                )
                d.release()
            return s

        def side(round_fn):
            sink = 0
            t0 = time.perf_counter()
            for _ in range(ROUNDS_PER_SIDE):
                sink += round_fn()
            dt = time.perf_counter() - t0
            return ROUNDS_PER_SIDE * READ_REGION / dt / 1e9, sink

        # warm both planes: connection, fds, page cache, dst faults
        pread_round()
        mapped_round()
        pairs = []
        for _ in range(N_PAIRS):
            a, sink_a = side(pread_round)
            b, sink_b = side(mapped_round)
            if (sink_a != want_round * ROUNDS_PER_SIDE
                    or sink_b != want_round * ROUNDS_PER_SIDE):
                raise SystemExit("BENCH FAILED: mapped A/B sums differ")
            pairs.append(
                {"pread_gbps": round(a, 3), "mapped_gbps": round(b, 3)}
            )
        med_a = float(np.median([p["pread_gbps"] for p in pairs]))
        med_b = float(np.median([p["mapped_gbps"] for p in pairs]))
        out["ab_consume_mapped"] = {
            "pairs": pairs,
            "pread_consumed_gbps": round(med_a, 3),
            "mapped_consumed_gbps": round(med_b, 3),
            "mapped_speedup": round(med_b / med_a, 3) if med_a else None,
        }
        buf.free()
    finally:
        cli.stop()
        srv.stop()
    return out


def bench_striping_ab() -> dict:
    """Interleaved striped-vs-unstriped A/B pairs, SAME run.

    The reference stripes READs over multiple QPs (RdmaChannel.java
    rdma_channel_conn_count); this rig's counterpart levers are the
    same-host file-worker pool (conf ``fileWorkers``) and multiple data
    connections on the streamed plane. Round-over-round numbers from
    DIFFERENT runs can't separate striping from rig drift, so each pair
    here interleaves A (unstriped) and B (striped) back to back against
    the SAME server region — per-pair ratios are drift-immune. Both
    clients/channel sets stay alive across all pairs (workers never
    shrink; connections are cached), so warm-up cost lands before the
    first pair, not inside one side of it."""
    from sparkrdma_tpu.memory.buffer import TpuBuffer
    from sparkrdma_tpu.transport import FnListener
    from sparkrdma_tpu.transport.native_node import NativeTpuNode
    from sparkrdma_tpu.utils.config import TpuShuffleConf

    out = {}
    rng = np.random.default_rng(11)
    srv = NativeTpuNode(TpuShuffleConf(), "127.0.0.1", False, "ab-srv")
    n_blocks = READ_REGION // READ_BLOCK
    dsts = [memoryview(bytearray(READ_BLOCK)) for _ in range(n_blocks)]
    N_PAIRS = 3
    ROUNDS_PER_SIDE = 4

    def one_round(channels, mkey, label):
        # round-robin the region's blocks over the channel set (one
        # entry = unstriped; M entries = striped across M connections)
        evs, errs = [], []
        for i in range(n_blocks):
            ev = threading.Event()

            def fail(e, ev=ev):
                errs.append(e)
                ev.set()

            channels[i % len(channels)].read_in_queue(
                FnListener(lambda _, ev=ev: ev.set(), fail),
                [dsts[i]], [(mkey, i * READ_BLOCK, READ_BLOCK)],
            )
            evs.append(ev)
        for ev in evs:
            assert ev.wait(120), f"{label}: A/B read timed out"
        if errs:
            raise SystemExit(f"BENCH FAILED: {label} READ error: {errs[0]}")

    def timed_side(channels, mkey, label):
        t0 = time.perf_counter()
        for _ in range(ROUNDS_PER_SIDE):
            one_round(channels, mkey, label)
        dt = time.perf_counter() - t0
        return ROUNDS_PER_SIDE * READ_REGION / dt / 1e9

    def run_pairs(ch_a, ch_b, mkey, label):
        pairs = []
        for _ in range(N_PAIRS):
            a = timed_side(ch_a, mkey, label)
            b = timed_side(ch_b, mkey, label)
            pairs.append(
                {"unstriped_gbps": round(a, 3), "striped_gbps": round(b, 3)}
            )
        med_a = float(np.median([p["unstriped_gbps"] for p in pairs]))
        med_b = float(np.median([p["striped_gbps"] for p in pairs]))
        return {
            "pairs": pairs,
            "unstriped_gbps": round(med_a, 3),
            "striped_gbps": round(med_b, 3),
            "striped_speedup": round(med_b / med_a, 3) if med_a else None,
        }

    clients = []
    try:
        src = rng.integers(0, 256, size=READ_REGION, dtype=np.uint8)
        buf = TpuBuffer(srv.pd, READ_REGION, register=True)
        np.frombuffer(buf.view, dtype=np.uint8)[:] = src

        # --- pair set 1: same-host pread plane, fileWorkers 1 vs N ----
        conf_s = TpuShuffleConf()  # shipped default worker count
        cli_u = NativeTpuNode(
            TpuShuffleConf({"tpu.shuffle.fileWorkers": "1"}),
            "127.0.0.1", True, "ab-cli-unstriped",
        )
        cli_s = NativeTpuNode(conf_s, "127.0.0.1", True, "ab-cli-striped")
        clients += [cli_u, cli_s]
        ch_u = [cli_u.get_channel("127.0.0.1", srv.port)]
        ch_s = [cli_s.get_channel("127.0.0.1", srv.port)]
        one_round(ch_u, buf.mkey, "samehost-warm")
        one_round(ch_s, buf.mkey, "samehost-warm")
        if not np.array_equal(np.frombuffer(dsts[1], np.uint8),
                              src[READ_BLOCK: 2 * READ_BLOCK]):
            raise SystemExit("BENCH FAILED: A/B samehost READ bytes differ")
        res = run_pairs(ch_u, ch_s, buf.mkey, "samehost")
        res["striped_workers"] = conf_s.file_workers
        out["ab_samehost_fileworkers"] = res

        # --- pair set 2: streamed plane, 1 vs M data connections ------
        # fileFastPath=false makes the loopback client behave like a
        # remote peer: every block rides a socket, so connection count
        # is the striping lever (purpose-distinct channels are distinct
        # connections in the native plane's channel cache)
        M = 4
        cli_r = NativeTpuNode(
            TpuShuffleConf({"tpu.shuffle.fileFastPath": "false"}),
            "127.0.0.1", True, "ab-cli-streamed",
        )
        clients.append(cli_r)
        ch_many = [
            cli_r.get_channel("127.0.0.1", srv.port, purpose=f"data-{j}")
            for j in range(M)
        ]
        ch_one = ch_many[:1]
        one_round(ch_many, buf.mkey, "streamed-warm")
        fast, streamed = cli_r.read_path_stats()
        if fast != 0 or streamed == 0:
            raise SystemExit("BENCH FAILED: A/B streamed pull not streamed")
        if not np.array_equal(np.frombuffer(dsts[1], np.uint8),
                              src[READ_BLOCK: 2 * READ_BLOCK]):
            raise SystemExit("BENCH FAILED: A/B streamed READ bytes differ")
        res = run_pairs(ch_one, ch_many, buf.mkey, "streamed")
        res["striped_connections"] = M
        out["ab_streamed_connections"] = res
        buf.free()
    finally:
        for c in clients:
            c.stop()
        srv.stop()
    return out


def bench_iouring_read_ab(dry_run: bool = False) -> dict:
    """Interleaved pread-vs-io_uring backend A/B pairs, SAME run.

    The submission plane (DESIGN.md §24) lets the same-host read path
    swap backends under an unchanged caller: the A side forces
    ``readBackend=pread`` (per-run preadv2 scatter), the B side
    ``readBackend=iouring`` (batched SQEs, fixed buffers registered
    once per worker ring, one ``io_uring_enter`` per task). Same
    channel, same region, same rotating destination set; bytes are
    verified under BOTH backends before timing — the A/B's first job
    is proving byte identity, its second is measuring the syscall
    batching. Where io_uring is unavailable (old kernel, seccomp,
    ``SPARKRDMA_NATIVE_NO_IOURING`` build) the row records the
    degradation honestly instead of timing pread against itself. On a
    1-core page-cache-resident rig the win is bounded by syscall
    count, not I/O parallelism — ``cores`` is recorded so the ledger
    stays interpretable."""
    import os
    import tempfile

    from sparkrdma_tpu.memory.buffer import TpuBuffer
    from sparkrdma_tpu.transport import FnListener
    from sparkrdma_tpu.transport.native_node import NativeTpuNode
    from sparkrdma_tpu.utils.config import TpuShuffleConf

    out = {}
    rng = np.random.default_rng(23)
    srv = NativeTpuNode(TpuShuffleConf(), "127.0.0.1", False, "uab-srv")
    cli = NativeTpuNode(TpuShuffleConf(), "127.0.0.1", True, "uab-cli")
    n_blocks = READ_REGION // READ_BLOCK
    N_PAIRS = 1 if dry_run else 3
    ROUNDS_PER_SIDE = 2 if dry_run else 4
    dsts = [memoryview(bytearray(READ_BLOCK)) for _ in range(n_blocks)]
    try:
        ch = cli.get_channel("127.0.0.1", srv.port)
        src = rng.integers(0, 256, size=READ_REGION, dtype=np.uint8)
        buf = TpuBuffer(srv.pd, READ_REGION, register=True)
        np.frombuffer(buf.view, dtype=np.uint8)[:] = src

        def one_round(label):
            evs, errs = [], []
            for i in range(n_blocks):
                ev = threading.Event()

                def fail(e, ev=ev):
                    errs.append(e)
                    ev.set()

                ch.read_in_queue(
                    FnListener(lambda _, ev=ev: ev.set(), fail),
                    [dsts[i]], [(buf.mkey, i * READ_BLOCK, READ_BLOCK)],
                )
                evs.append(ev)
            for ev in evs:
                assert ev.wait(120), f"{label}: iouring A/B read timed out"
            if errs:
                raise SystemExit(
                    f"BENCH FAILED: iouring A/B READ error: {errs[0]}"
                )

        def verify(label):
            for i in (0, 1, n_blocks - 1):
                if not np.array_equal(
                    np.frombuffer(dsts[i], np.uint8),
                    src[i * READ_BLOCK: (i + 1) * READ_BLOCK],
                ):
                    raise SystemExit(
                        f"BENCH FAILED: {label} READ bytes differ"
                    )

        def timed_side(backend):
            cli.set_read_backend(backend)
            t0 = time.perf_counter()
            for _ in range(ROUNDS_PER_SIDE):
                one_round(backend)
            dt = time.perf_counter() - t0
            return ROUNDS_PER_SIDE * READ_REGION / dt / 1e9

        # warm + byte-identity check, BOTH backends, before any timing
        cli.set_read_backend("iouring")
        one_round("iouring-warm")
        verify("iouring")
        stats = cli.sq_stats()
        cli.set_read_backend("pread")
        one_round("pread-warm")
        verify("pread")
        fast, _ = cli.read_path_stats()
        if fast == 0:
            raise SystemExit(
                "BENCH FAILED: iouring A/B never took the fast path"
            )
        row = {
            "uring_compiled": stats.get("uring_compiled"),
            "iouring_available": stats.get("backend") == "iouring",
            "backend_fallbacks": stats.get("backend_fallbacks"),
            "cores": os.cpu_count() or 1,
        }
        if stats.get("backend") != "iouring":
            # degradation is the result, not an error: pread served the
            # warm round byte-identically and the fallback was counted
            row["skip_reason"] = (
                "io_uring unavailable on this rig/build; timing pread "
                "against itself would be noise"
            )
            out["ab_iouring_read"] = row
            buf.free()
            return out

        s0 = cli.sq_stats()
        pairs = []
        for _ in range(N_PAIRS):
            a = timed_side("pread")
            b = timed_side("iouring")
            pairs.append(
                {"pread_gbps": round(a, 3), "iouring_gbps": round(b, 3)}
            )
        s1 = cli.sq_stats()
        med_a = float(np.median([p["pread_gbps"] for p in pairs]))
        med_b = float(np.median([p["iouring_gbps"] for p in pairs]))

        # machine roofline for this path: raw page-cache pread of the
        # same volume into the same rotating destination set
        with tempfile.NamedTemporaryFile(dir="/dev/shm") as f:
            f.write(src.tobytes())
            f.flush()
            rfd = f.fileno()
            for i in range(n_blocks):
                os.preadv(rfd, [dsts[i]], i * READ_BLOCK)
            t0 = time.perf_counter()
            moved = 0
            for _ in range(ROUNDS_PER_SIDE):
                for i in range(n_blocks):
                    moved += os.preadv(rfd, [dsts[i]], i * READ_BLOCK)
            roofline = moved / (time.perf_counter() - t0) / 1e9

        d_submits = s1["submits"] - s0["submits"]
        d_batches = s1["batches"] - s0["batches"]
        row.update({
            "pairs": pairs,
            "pread_gbps": round(med_a, 3),
            "iouring_gbps": round(med_b, 3),
            "iouring_speedup": round(med_b / med_a, 3) if med_a else None,
            "sq_submits": d_submits,
            "sq_batches": d_batches,
            "sqe_batching_factor": (
                round(d_submits / d_batches, 2) if d_batches else None
            ),
            "pread_roofline_gbps": round(roofline, 3),
            "roofline_fraction": (
                round(med_b / roofline, 3) if roofline else None
            ),
        })
        out["ab_iouring_read"] = row
        buf.free()
    finally:
        cli.stop()
        srv.stop()
    return out


def bench_consume_sharded_ab(dry_run: bool = False) -> dict:
    """Interleaved inline-vs-sharded consume A/B pairs, SAME run.

    ``tpu.shuffle.native.consumeWorkers`` shards READ_DONE completion
    work (checksum + decode + delivery) across lanes routed by channel
    (DESIGN.md §24); this A/B isolates exactly that seam. Both sides
    run the SAME fetch-to-consumed shape — read a region's blocks
    round-robin over 4 connections, uint8-sum every byte in the
    completion listener — but the A client consumes inline on its poll
    thread (``consumeWorkers=1``) while the B client's 4 lanes run the
    sums concurrently with the poll loop and each other (the sum
    releases the GIL). Sums are verified both sides every round, so
    sharding is proven order-safe and byte-identical before it is
    credited with anything. On a 1-core rig the lanes can only overlap
    consume with poll-loop bookkeeping, so ~1x is honest — the ≥90%
    consume-roofline expectation applies where cores exist (``cores``
    recorded)."""
    import os

    from sparkrdma_tpu.memory.buffer import TpuBuffer
    from sparkrdma_tpu.transport import FnListener
    from sparkrdma_tpu.transport.native_node import NativeTpuNode
    from sparkrdma_tpu.utils.config import TpuShuffleConf

    out = {}
    rng = np.random.default_rng(29)
    LANES = 4
    srv = NativeTpuNode(TpuShuffleConf(), "127.0.0.1", False, "sab-srv")
    cli_i = NativeTpuNode(
        TpuShuffleConf({"tpu.shuffle.native.consumeWorkers": "1"}),
        "127.0.0.1", True, "sab-cli-inline",
    )
    cli_s = NativeTpuNode(
        TpuShuffleConf({"tpu.shuffle.native.consumeWorkers": str(LANES)}),
        "127.0.0.1", True, "sab-cli-sharded",
    )
    n_blocks = READ_REGION // READ_BLOCK
    N_PAIRS = 1 if dry_run else 3
    ROUNDS_PER_SIDE = 2 if dry_run else 4
    dsts = [memoryview(bytearray(READ_BLOCK)) for _ in range(n_blocks)]
    try:
        src = rng.integers(0, 256, size=READ_REGION, dtype=np.uint8)
        buf = TpuBuffer(srv.pd, READ_REGION, register=True)
        np.frombuffer(buf.view, dtype=np.uint8)[:] = src
        want_round = int(np.add.reduce(src, dtype=np.int64))
        # lanes shard by channel: spread the region over LANES distinct
        # connections so the B side actually exercises every lane
        ch_i = [
            cli_i.get_channel("127.0.0.1", srv.port, purpose=f"data-{j}")
            for j in range(LANES)
        ]
        ch_s = [
            cli_s.get_channel("127.0.0.1", srv.port, purpose=f"data-{j}")
            for j in range(LANES)
        ]

        def one_round(channels, label):
            sums = [0] * n_blocks
            evs, errs = [], []
            for i in range(n_blocks):
                ev = threading.Event()

                def ok(_, i=i, ev=ev):
                    # THE consume: full-speed sum of the landed block,
                    # on whatever thread the node's consume plane picks
                    sums[i] = int(np.add.reduce(
                        np.frombuffer(dsts[i], np.uint8), dtype=np.int64
                    ))
                    ev.set()

                def fail(e, ev=ev):
                    errs.append(e)
                    ev.set()

                channels[i % len(channels)].read_in_queue(
                    FnListener(ok, fail),
                    [dsts[i]], [(buf.mkey, i * READ_BLOCK, READ_BLOCK)],
                )
                evs.append(ev)
            for ev in evs:
                assert ev.wait(120), f"{label}: consume A/B read timed out"
            if errs:
                raise SystemExit(
                    f"BENCH FAILED: {label} READ error: {errs[0]}"
                )
            if sum(sums) != want_round:
                raise SystemExit(
                    f"BENCH FAILED: {label} consume A/B sums differ"
                )

        def timed_side(channels, label):
            t0 = time.perf_counter()
            for _ in range(ROUNDS_PER_SIDE):
                one_round(channels, label)
            dt = time.perf_counter() - t0
            return ROUNDS_PER_SIDE * READ_REGION / dt / 1e9

        one_round(ch_i, "inline-warm")
        one_round(ch_s, "sharded-warm")
        if cli_s.sq_stats().get("consume_workers") != LANES:
            raise SystemExit(
                "BENCH FAILED: sharded client has no consume lanes"
            )
        pairs = []
        for _ in range(N_PAIRS):
            a = timed_side(ch_i, "inline")
            b = timed_side(ch_s, "sharded")
            pairs.append(
                {"inline_gbps": round(a, 3), "sharded_gbps": round(b, 3)}
            )
        med_a = float(np.median([p["inline_gbps"] for p in pairs]))
        med_b = float(np.median([p["sharded_gbps"] for p in pairs]))

        # this comparison's machine limit: the one-pass consume over
        # the same rotating set with delivery assumed free
        t0 = time.perf_counter()
        moved = 0
        for _ in range(ROUNDS_PER_SIDE):
            for d in dsts:
                np.add.reduce(np.frombuffer(d, np.uint8), dtype=np.int64)
                moved += READ_BLOCK
        roofline = moved / (time.perf_counter() - t0) / 1e9

        out["ab_consume_sharded"] = {
            "pairs": pairs,
            "inline_consumed_gbps": round(med_a, 3),
            "sharded_consumed_gbps": round(med_b, 3),
            "sharded_speedup": round(med_b / med_a, 3) if med_a else None,
            "consume_workers": LANES,
            "cores": os.cpu_count() or 1,
            "consume_roofline_gbps": round(roofline, 3),
            "roofline_fraction": (
                round(med_b / roofline, 3) if roofline else None
            ),
        }
        buf.free()
    finally:
        cli_i.stop()
        cli_s.stop()
        srv.stop()
    return out


def bench_device_fetch_ab(dry_run: bool = False) -> dict:
    """Interleaved device-pull vs host-fetch A/B pairs, SAME run.

    The device fetch plane (DESIGN.md §17) moves arena-resident blocks
    HBM→HBM behind the same resolver API the host path uses; this A/B
    toggles ``deviceFetch.enabled`` between sides of each pair so both
    fetch the SAME published blocks through the same
    ``fetch_device_blocks`` call. Both sides byte-verify against the
    source; the B side additionally proves the pulls actually engaged
    (plane counter moved, zero fallbacks). Under ``JAX_PLATFORMS=cpu``
    the mover is the emulated ``jax.device_put`` path, so ~1.0x is the
    expected speedup — the row exists to keep the plane measured and
    regression-gated, and to light up on a real ICI mesh.

    ``dry_run`` shrinks the volume for the CI obs smoke
    (``bench.py --ab device_fetch``)."""
    from sparkrdma_tpu.obs import get_registry
    from sparkrdma_tpu.shuffle.device_io import DeviceShuffleIO
    from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle, HashPartitioner
    from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
    from sparkrdma_tpu.utils.config import TpuShuffleConf

    out = {}
    n_parts = 4 if dry_run else 8
    block = (256 << 10) if dry_run else (2 << 20)
    n_pairs = 1 if dry_run else 3
    rounds = 2 if dry_run else 4
    conf = TpuShuffleConf()
    driver = TpuShuffleManager(conf, is_driver=True)
    ex_map = TpuShuffleManager(conf, is_driver=False, executor_id="dfab-map")
    ex_red = TpuShuffleManager(conf, is_driver=False, executor_id="dfab-red")
    driver.register_shuffle(
        BaseShuffleHandle(
            shuffle_id=71, num_maps=1, partitioner=HashPartitioner(n_parts)
        )
    )
    io_map, io_red = DeviceShuffleIO(ex_map), DeviceShuffleIO(ex_red)
    rng = np.random.default_rng(31)
    data = {
        p: rng.integers(0, 256, block, np.uint8) for p in range(n_parts)
    }
    total = n_parts * block
    reg = get_registry()
    pulls = reg.counter("device_fetch.plane.pulls", role="dfab-red")
    fallbacks = reg.counter("device_fetch.plane.fallbacks", role="dfab-red")
    try:
        io_map.publish_device_blocks(71, data)

        def fetch_round(verify: bool) -> None:
            got = io_red.fetch_device_blocks(71, 0, n_parts, timeout_s=120)
            try:
                if verify:
                    for p in range(n_parts):
                        if bytes(got[p][0].read(0, block)) != data[p].tobytes():
                            raise SystemExit(
                                "BENCH FAILED: device-fetch A/B bytes differ"
                            )
            finally:
                for bufs in got.values():
                    for b in bufs:
                        b.free()

        def side(enabled: bool):
            conf.set("tpu.shuffle.deviceFetch.enabled", str(enabled).lower())
            fetch_round(verify=True)  # warm + byte-identity, untimed
            t0 = time.perf_counter()
            for _ in range(rounds):
                fetch_round(verify=False)
            dt = time.perf_counter() - t0
            return rounds * total / dt / 1e9

        pairs = []
        for _ in range(n_pairs):
            a = side(False)
            p0, f0 = pulls.value, fallbacks.value
            b = side(True)
            if pulls.value - p0 < (rounds + 1) * n_parts:
                raise SystemExit(
                    "BENCH FAILED: device-fetch A/B pulls did not engage"
                )
            if fallbacks.value != f0:
                raise SystemExit(
                    "BENCH FAILED: device-fetch A/B fell back mid-pair"
                )
            pairs.append(
                {"host_gbps": round(a, 3), "device_gbps": round(b, 3)}
            )
        med_a = float(np.median([p["host_gbps"] for p in pairs]))
        med_b = float(np.median([p["device_gbps"] for p in pairs]))
        out["ab_device_fetch"] = {
            "pairs": pairs,
            "host_fetch_gbps": round(med_a, 3),
            "device_fetch_gbps": round(med_b, 3),
            "speedup": round(med_b / med_a, 3) if med_a else None,
            "mover": "pallas-ici" if _is_tpu() else "emulated-device-put",
        }
    finally:
        io_red.stop()
        io_map.stop()
        ex_red.stop()
        ex_map.stop()
        driver.stop()
    return out


def bench_concurrent_jobs_ab(dry_run: bool = False) -> dict:
    """Interleaved sequential-vs-concurrent job serving A/B, SAME run.

    The tenancy tentpole's headline: one TpuContext serving K jobs from
    K tenants concurrently (admission + fair-share pools, DESIGN.md
    §19) against the same K jobs run back to back. Each side runs the
    SAME job set on the SAME context (warm executors, warm pools);
    aggregate MB/s is the writer-bytes moved over the side's wall
    clock, so the ratio is the serving-concurrency win, not a cache
    artifact. Every job's result is verified on both sides.

    On a 1-core rig the concurrent side mostly overlaps I/O waits and
    ~1x is honest; the ≥1.5x acceptance gate applies where parallelism
    exists (recorded as ``cores`` so the ledger is interpretable)."""
    import os

    from sparkrdma_tpu.engine.context import TpuContext
    from sparkrdma_tpu.obs import get_registry
    from sparkrdma_tpu.utils.config import TpuShuffleConf

    n_jobs = 4
    n_rows = 2_000 if dry_run else 20_000
    n_parts = 4
    n_pairs = 1 if dry_run else 3
    reg = get_registry()
    out = {}
    conf = TpuShuffleConf()
    with TpuContext(num_executors=2, conf=conf, task_threads=n_jobs) as ctx:
        def make_job(j):
            # wide key space: map-side aggregation barely collapses it,
            # so the shuffle moves real bytes and MB/s means throughput
            mod = 4093 + j
            rdd = (
                ctx.parallelize(range(n_rows), n_parts)
                .map(lambda x, m=mod: (x % m, x))
                .reduce_by_key(lambda a, b: a + b, num_partitions=n_parts)
            )
            expected = {}
            for x in range(n_rows):
                expected[x % mod] = expected.get(x % mod, 0) + x
            return rdd, expected

        def run_one(j):
            rdd, expected = make_job(j)
            got = dict(ctx.run_job(rdd, tenant=f"t{j}"))
            if got != expected:
                raise SystemExit(
                    f"BENCH FAILED: concurrent-jobs A/B job {j} wrong result"
                )

        def bytes_written():
            snap = reg.snapshot(prefix="writer.bytes_written")
            return sum(snap.get("counters", {}).values())

        def sequential_side():
            b0 = bytes_written()
            t0 = time.perf_counter()
            for j in range(n_jobs):
                run_one(j)
            dt = time.perf_counter() - t0
            return (bytes_written() - b0) / dt / 1e6

        def concurrent_side():
            errs = []

            def worker(j):
                try:
                    run_one(j)
                except BaseException as e:  # noqa: BLE001
                    errs.append(e)

            b0 = bytes_written()
            t0 = time.perf_counter()
            threads = [
                threading.Thread(target=worker, args=(j,))
                for j in range(n_jobs)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            dt = time.perf_counter() - t0
            if errs:
                raise errs[0]
            return (bytes_written() - b0) / dt / 1e6

        run_one(0)  # warm: executors, pools, codecs
        pairs = []
        for _ in range(n_pairs):
            a = sequential_side()
            b = concurrent_side()
            pairs.append(
                {"sequential_mbps": round(a, 3), "concurrent_mbps": round(b, 3)}
            )
    med_a = float(np.median([p["sequential_mbps"] for p in pairs]))
    med_b = float(np.median([p["concurrent_mbps"] for p in pairs]))
    speedup = round(med_b / med_a, 3) if med_a else None
    cores = os.cpu_count() or 1
    # the ≥1.5x gate only MEANS anything where parallelism exists;
    # everywhere this row is checked (CI smoke included) the consumer
    # must branch on gate_evaluated and surface gate_skip_reason
    # loudly instead of silently passing on a small rig
    gate_evaluated = cores >= 4 and speedup is not None
    gate_skip_reason = None
    if not gate_evaluated:
        gate_skip_reason = (
            f"only {cores} core(s): concurrency gate needs >= 4"
            if cores < 4 else "no speedup measured"
        )
    if gate_evaluated and speedup < 1.5:
        raise SystemExit(
            f"BENCH FAILED: concurrent serving {speedup}x < 1.5x on a "
            f"{cores}-core rig"
        )
    out["ab_concurrent_jobs"] = {
        "pairs": pairs,
        "sequential_mbps": round(med_a, 3),
        "concurrent_mbps": round(med_b, 3),
        "concurrency_speedup": speedup,
        "jobs": n_jobs,
        "cores": cores,
        "gate_evaluated": gate_evaluated,
        "gate_skip_reason": gate_skip_reason,
    }
    return out


def bench_profiler_overhead_ab(dry_run: bool = False) -> dict:
    """Interleaved profiler-off vs profiler-on A/B on the SAME warm
    context (obs/profiler.py, docs/OBSERVABILITY.md "Continuous
    profiling").

    Both sides run the same sequential job set on one TpuContext; the
    "on" side additionally runs the wall-clock sampler at the DEFAULT
    rate (``tpu.shuffle.obs.profile.hz``), so the throughput delta is
    the profiler's whole marginal cost. The acceptance budget is ≤2%
    — but wall-clock noise on a shared rig is routinely bigger than
    that, so the gate is only *evaluated* when the interleaved pairs
    were stable enough to resolve it (pair spread ≤ 4%); otherwise it
    SKIPS LOUDLY with ``gate_skip_reason``, never a silent pass."""
    from sparkrdma_tpu.engine.context import TpuContext
    from sparkrdma_tpu.obs import get_registry
    from sparkrdma_tpu.obs.profiler import SamplingProfiler, get_profiler
    from sparkrdma_tpu.utils.config import TpuShuffleConf

    n_jobs = 2
    n_rows = 2_000 if dry_run else 20_000
    n_parts = 4
    n_pairs = 2 if dry_run else 5
    reg = get_registry()
    default_hz = TpuShuffleConf().profile_hz
    # keep the off side honest: pause any ambient process sampler (the
    # bench harness runs one for its own artifact) for the A/B's span
    ambient = get_profiler()
    ambient_was_running = ambient is not None and ambient.running
    if ambient_was_running:
        ambient.stop()
    # the context under test runs with the profiler knob OFF — the "on"
    # side's sampler below is the only one observing either side
    conf = TpuShuffleConf({"tpu.shuffle.obs.profile.enabled": "false"})
    out = {}
    try:
        with TpuContext(num_executors=2, conf=conf, task_threads=2) as ctx:
            def run_jobs():
                for j in range(n_jobs):
                    mod = 4093 + j
                    rdd = (
                        ctx.parallelize(range(n_rows), n_parts)
                        .map(lambda x, m=mod: (x % m, x))
                        .reduce_by_key(lambda a, b: a + b,
                                       num_partitions=n_parts)
                    )
                    if not ctx.run_job(rdd):
                        raise SystemExit(
                            "BENCH FAILED: profiler A/B job returned nothing"
                        )

            def bytes_written():
                snap = reg.snapshot(prefix="writer.bytes_written")
                return sum(snap.get("counters", {}).values())

            def one_side(profiler):
                if profiler is not None:
                    profiler.start()
                b0 = bytes_written()
                t0 = time.perf_counter()
                try:
                    run_jobs()
                finally:
                    if profiler is not None:
                        profiler.stop()
                return (bytes_written() - b0) / (time.perf_counter() - t0) / 1e6

            run_jobs()  # warm: executors, pools, codecs
            sampler = SamplingProfiler(reg, role="bench-ab", hz=default_hz)
            pairs = []
            for _ in range(n_pairs):
                a = one_side(None)
                b = one_side(sampler)
                pairs.append({"off_mbps": round(a, 3), "on_mbps": round(b, 3)})
    finally:
        if ambient_was_running:
            ambient.start()
    med_a = float(np.median([p["off_mbps"] for p in pairs]))
    med_b = float(np.median([p["on_mbps"] for p in pairs]))
    overhead_pct = round((1.0 - med_b / med_a) * 100.0, 3) if med_a else None
    ratios = [p["on_mbps"] / p["off_mbps"] for p in pairs if p["off_mbps"]]
    pair_spread_pct = (
        round((max(ratios) - min(ratios)) * 100.0, 3) if ratios else None
    )
    samples = int(reg.snapshot(prefix="profile.samples")
                  .get("counters", {})
                  .get("profile.samples{role=bench-ab}", 0))
    gate_evaluated = (
        not dry_run
        and overhead_pct is not None
        and pair_spread_pct is not None
        and pair_spread_pct <= 4.0
        and samples > 0
    )
    gate_skip_reason = None
    if not gate_evaluated:
        if dry_run:
            gate_skip_reason = (
                "dry run: volume too small to resolve a 2% delta"
            )
        elif samples == 0:
            gate_skip_reason = "sampler recorded zero samples"
        elif pair_spread_pct is None or overhead_pct is None:
            gate_skip_reason = "no throughput measured"
        else:
            gate_skip_reason = (
                f"pair spread {pair_spread_pct}% > 4%: run too noisy to "
                "resolve a 2% overhead budget"
            )
    if gate_evaluated and overhead_pct > 2.0:
        raise SystemExit(
            f"BENCH FAILED: profiler overhead {overhead_pct}% > 2% at "
            f"{default_hz} Hz (off {med_a:.1f} MB/s, on {med_b:.1f} MB/s)"
        )
    out["ab_profiler_overhead"] = {
        "pairs": pairs,
        "off_mbps": round(med_a, 3),
        "on_mbps": round(med_b, 3),
        "overhead_pct": overhead_pct,
        "pair_spread_pct": pair_spread_pct,
        "hz": default_hz,
        "profile_samples": samples,
        "gate_evaluated": gate_evaluated,
        "gate_skip_reason": gate_skip_reason,
    }
    return out


def bench_slo_overhead_ab(dry_run: bool = False) -> dict:
    """Interleaved SLO-evaluator-off vs -on A/B on the SAME warm context
    (obs/slo.py, docs/OBSERVABILITY.md "SLOs & automated diagnosis").

    Both sides run the same sequential job set on one TpuContext whose
    driver hub evaluates every 100 ms with a latency objective installed
    (a deliberately unreachable p99 bar, so no breach/diagnosis path
    fires — this measures the steady-state cost of burn-rate evaluation
    itself); the "off" side flips ``hub.slo.enabled`` so heartbeats skip
    evaluation entirely. The acceptance budget is ≤2%, evaluated only
    when the interleaved pairs are stable enough to resolve it (pair
    spread ≤ 4%); otherwise it SKIPS LOUDLY with ``gate_skip_reason``,
    never a silent pass."""
    from sparkrdma_tpu.engine.context import TpuContext
    from sparkrdma_tpu.obs import get_registry
    from sparkrdma_tpu.utils.config import TpuShuffleConf

    n_jobs = 2
    n_rows = 2_000 if dry_run else 20_000
    n_parts = 4
    n_pairs = 2 if dry_run else 5
    reg = get_registry()
    eval_interval_ms = 100
    conf = TpuShuffleConf({
        "tpu.shuffle.obs.profile.enabled": "false",
        "tpu.shuffle.obs.telemetry.intervalMs": "100",
        "tpu.shuffle.obs.slo.evalIntervalMs": str(eval_interval_ms),
        # install the latency objective but keep it unbreachable: the
        # A/B measures evaluation cost, not breach handling
        "tpu.shuffle.obs.slo.taskP99Ms": "600000",
    })

    def evaluations():
        snap = reg.snapshot(prefix="slo.evaluations")
        return sum(snap.get("counters", {}).values())

    with TpuContext(num_executors=2, conf=conf, task_threads=2) as ctx:
        hub = ctx.driver.telemetry
        if hub is None:
            raise SystemExit("BENCH FAILED: slo A/B needs driver telemetry")

        def run_jobs():
            for j in range(n_jobs):
                mod = 4093 + j
                rdd = (
                    ctx.parallelize(range(n_rows), n_parts)
                    .map(lambda x, m=mod: (x % m, x))
                    .reduce_by_key(lambda a, b: a + b,
                                   num_partitions=n_parts)
                )
                if not ctx.run_job(rdd):
                    raise SystemExit(
                        "BENCH FAILED: slo A/B job returned nothing"
                    )

        def bytes_written():
            snap = reg.snapshot(prefix="writer.bytes_written")
            return sum(snap.get("counters", {}).values())

        def one_side(enabled):
            hub.slo.enabled = enabled
            b0 = bytes_written()
            t0 = time.perf_counter()
            try:
                run_jobs()
            finally:
                hub.slo.enabled = True
            return (bytes_written() - b0) / (time.perf_counter() - t0) / 1e6

        run_jobs()  # warm: executors, pools, codecs
        e0 = evaluations()
        pairs = []
        for _ in range(n_pairs):
            a = one_side(False)
            b = one_side(True)
            pairs.append({"off_mbps": round(a, 3), "on_mbps": round(b, 3)})
        evals = int(evaluations() - e0)
        breaches = len(hub.slo.breaches)
    med_a = float(np.median([p["off_mbps"] for p in pairs]))
    med_b = float(np.median([p["on_mbps"] for p in pairs]))
    overhead_pct = round((1.0 - med_b / med_a) * 100.0, 3) if med_a else None
    ratios = [p["on_mbps"] / p["off_mbps"] for p in pairs if p["off_mbps"]]
    pair_spread_pct = (
        round((max(ratios) - min(ratios)) * 100.0, 3) if ratios else None
    )
    gate_evaluated = (
        not dry_run
        and overhead_pct is not None
        and pair_spread_pct is not None
        and pair_spread_pct <= 4.0
        and evals > 0
    )
    gate_skip_reason = None
    if not gate_evaluated:
        if dry_run:
            gate_skip_reason = (
                "dry run: volume too small to resolve a 2% delta"
            )
        elif evals == 0:
            gate_skip_reason = "SLO engine recorded zero evaluations"
        elif pair_spread_pct is None or overhead_pct is None:
            gate_skip_reason = "no throughput measured"
        else:
            gate_skip_reason = (
                f"pair spread {pair_spread_pct}% > 4%: run too noisy to "
                "resolve a 2% overhead budget"
            )
    if gate_evaluated and overhead_pct > 2.0:
        raise SystemExit(
            f"BENCH FAILED: SLO evaluator overhead {overhead_pct}% > 2% at "
            f"{eval_interval_ms} ms cadence (off {med_a:.1f} MB/s, "
            f"on {med_b:.1f} MB/s)"
        )
    return {
        "ab_slo_overhead": {
            "pairs": pairs,
            "off_mbps": round(med_a, 3),
            "on_mbps": round(med_b, 3),
            "overhead_pct": overhead_pct,
            "pair_spread_pct": pair_spread_pct,
            "eval_interval_ms": eval_interval_ms,
            "slo_evaluations": evals,
            "slo_breaches": breaches,
            "gate_evaluated": gate_evaluated,
            "gate_skip_reason": gate_skip_reason,
        }
    }


def bench_journal_overhead_ab(dry_run: bool = False) -> dict:
    """Interleaved event-journal-off vs -on A/B on the SAME warm context
    (obs/journal.py, docs/OBSERVABILITY.md "Event journal & capacity
    plane").

    Both sides run the same sequential job set on one TpuContext with
    100 ms heartbeats; each job additionally drives a burst of emits
    through the module-level seam so the measurement covers the full
    plane — emit under lock, HLC tick, heartbeat shipping with one-beat
    redundancy, and hub-side merge — not just the quiet steady state.
    The "off" side flips :func:`journal.set_enabled`, which parks the
    journal (seq continuity preserved) and reduces every emit site to a
    module-global load + None check. The acceptance budget is ≤2%,
    evaluated only when the interleaved pairs are stable enough to
    resolve it (pair spread ≤ 4%); otherwise it SKIPS LOUDLY with
    ``gate_skip_reason``, never a silent pass."""
    from sparkrdma_tpu.engine.context import TpuContext
    from sparkrdma_tpu.obs import get_registry
    from sparkrdma_tpu.obs import journal as journal_mod
    from sparkrdma_tpu.utils.config import TpuShuffleConf

    n_jobs = 2
    n_rows = 2_000 if dry_run else 20_000
    n_parts = 4
    n_pairs = 2 if dry_run else 5
    burst = 64  # emits per job through the module seam
    reg = get_registry()
    conf = TpuShuffleConf({
        "tpu.shuffle.obs.profile.enabled": "false",
        "tpu.shuffle.obs.telemetry.intervalMs": "100",
    })

    def journal_counter(name):
        snap = reg.snapshot(prefix=name)
        return sum(snap.get("counters", {}).values())

    with TpuContext(num_executors=2, conf=conf, task_threads=2) as ctx:
        hub = ctx.driver.telemetry
        if hub is None:
            raise SystemExit(
                "BENCH FAILED: journal A/B needs driver telemetry"
            )

        def run_jobs():
            for j in range(n_jobs):
                mod = 4093 + j
                rdd = (
                    ctx.parallelize(range(n_rows), n_parts)
                    .map(lambda x, m=mod: (x % m, x))
                    .reduce_by_key(lambda a, b: a + b,
                                   num_partitions=n_parts)
                )
                # incident-storm sized burst at a real emit site shape:
                # a no-op on the off side, the full ring/ship/merge
                # plane on the on side
                for i in range(burst):
                    journal_mod.emit("bench.tick", role="bench", beat=i)
                if not ctx.run_job(rdd):
                    raise SystemExit(
                        "BENCH FAILED: journal A/B job returned nothing"
                    )

        def bytes_written():
            snap = reg.snapshot(prefix="writer.bytes_written")
            return sum(snap.get("counters", {}).values())

        def one_side(enabled):
            journal_mod.set_enabled(enabled)
            b0 = bytes_written()
            t0 = time.perf_counter()
            try:
                run_jobs()
            finally:
                journal_mod.set_enabled(True)
            return (bytes_written() - b0) / (time.perf_counter() - t0) / 1e6

        run_jobs()  # warm: executors, pools, codecs
        ev0 = journal_counter("journal.events")
        mg0 = journal_counter("journal.merged")
        pairs = []
        for _ in range(n_pairs):
            a = one_side(False)
            b = one_side(True)
            pairs.append({"off_mbps": round(a, 3), "on_mbps": round(b, 3)})
        events = int(journal_counter("journal.events") - ev0)
        merged = int(journal_counter("journal.merged") - mg0)
    med_a = float(np.median([p["off_mbps"] for p in pairs]))
    med_b = float(np.median([p["on_mbps"] for p in pairs]))
    overhead_pct = round((1.0 - med_b / med_a) * 100.0, 3) if med_a else None
    ratios = [p["on_mbps"] / p["off_mbps"] for p in pairs if p["off_mbps"]]
    pair_spread_pct = (
        round((max(ratios) - min(ratios)) * 100.0, 3) if ratios else None
    )
    gate_evaluated = (
        not dry_run
        and overhead_pct is not None
        and pair_spread_pct is not None
        and pair_spread_pct <= 4.0
        and events > 0
    )
    gate_skip_reason = None
    if not gate_evaluated:
        if dry_run:
            gate_skip_reason = (
                "dry run: volume too small to resolve a 2% delta"
            )
        elif events == 0:
            gate_skip_reason = "journal recorded zero events on the on side"
        elif pair_spread_pct is None or overhead_pct is None:
            gate_skip_reason = "no throughput measured"
        else:
            gate_skip_reason = (
                f"pair spread {pair_spread_pct}% > 4%: run too noisy to "
                "resolve a 2% overhead budget"
            )
    if gate_evaluated and overhead_pct > 2.0:
        raise SystemExit(
            f"BENCH FAILED: event journal overhead {overhead_pct}% > 2% "
            f"(off {med_a:.1f} MB/s, on {med_b:.1f} MB/s, "
            f"{events} events emitted)"
        )
    return {
        "ab_journal_overhead": {
            "pairs": pairs,
            "off_mbps": round(med_a, 3),
            "on_mbps": round(med_b, 3),
            "overhead_pct": overhead_pct,
            "pair_spread_pct": pair_spread_pct,
            "journal_events": events,
            "journal_merged": merged,
            "burst_per_job": burst,
            "gate_evaluated": gate_evaluated,
            "gate_skip_reason": gate_skip_reason,
        }
    }


def _is_tpu() -> bool:
    try:
        from sparkrdma_tpu.ops.remote_copy import is_tpu_mesh

        return is_tpu_mesh()
    except Exception:
        return False


def _socket_roofline() -> float:
    """Raw single-core loopback TCP throughput at the bench's block
    size — the streamed plane's machine limit on this rig. Moves the
    same volume as the paths it calibrates (a short probe jitters
    enough on a loaded 1-core rig to land under the plane it bounds)."""
    import socket

    from sparkrdma_tpu.transport.wire import read_into

    block = READ_BLOCK
    total = READ_TOTAL
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    src = np.random.default_rng(3).integers(
        0, 256, block, dtype=np.uint8
    ).tobytes()

    def server():
        c, _ = srv.accept()
        c.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        for _ in range(total // block):
            c.sendall(src)
        c.close()

    t = threading.Thread(target=server, daemon=True)
    t.start()
    cli = socket.create_connection(("127.0.0.1", port))
    cli.settimeout(120)
    try:
        dsts = [memoryview(bytearray(block)) for _ in range(8)]

        read_into(cli, dsts[0])  # warm
        t0 = time.perf_counter()
        n = 0
        for i in range(1, total // block):
            read_into(cli, dsts[i % 8])
            n += block
        gbps = n / (time.perf_counter() - t0) / 1e9
    finally:
        cli.close()
        srv.close()
        t.join(10)
    return round(gbps, 3)


def _sendfile_roofline() -> float:
    """Raw loopback throughput when the sender is ``sendfile`` from a
    page-cache-resident shm file (no sender userspace copy) and the
    receiver recv_intos a rotating destination set — the machine limit
    for the streamed-sendfile plane on this rig."""
    import os
    import socket
    import tempfile

    from sparkrdma_tpu.transport.wire import read_into

    block = READ_BLOCK
    total = READ_TOTAL
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    with tempfile.NamedTemporaryFile(dir="/dev/shm") as f:
        f.write(np.random.default_rng(5).integers(
            0, 256, block, dtype=np.uint8).tobytes())
        f.flush()
        sfd = f.fileno()

        def server():
            c, _ = srv.accept()
            c.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            try:
                for _ in range(total // block):
                    sent = 0
                    while sent < block:
                        sent += os.sendfile(c.fileno(), sfd, sent, block - sent)
            finally:
                c.close()

        t = threading.Thread(target=server, daemon=True)
        t.start()
        cli = socket.create_connection(("127.0.0.1", port))
        cli.settimeout(120)
        try:
            dsts = [memoryview(bytearray(block)) for _ in range(8)]
            read_into(cli, dsts[0])  # warm
            t0 = time.perf_counter()
            n = 0
            for i in range(1, total // block):
                read_into(cli, dsts[i % 8])
                n += block
            gbps = n / (time.perf_counter() - t0) / 1e9
        finally:
            cli.close()
            srv.close()
            t.join(10)
    return round(gbps, 3)


# ---------------------------------------------------------------------------
# columnar block format: decode-path A/B (DESIGN.md §25)
# ---------------------------------------------------------------------------

def bench_columnar_decode_ab(dry_run: bool = False) -> dict:
    """Interleaved pickle-decode vs columnar-decode A/B over identical
    record sets (DESIGN.md §25, ``bench.py --ab columnar_decode``).

    Both sides consume the exact framed partition stream the reduce
    pipeline fetches (length-prefixed frames through
    ``iter_compressed_blocks``), built from the same (uint32, int64)
    records by the real writers. The PICKLE side measures the legacy
    decode stage end to end: zlib decompress + ``load_buffer`` row
    materialization. The COLUMNAR side measures what that stage
    degenerated to for the analytic/device consumers: header validation
    + ``np.frombuffer`` column views, plus a full-column reduction so
    every landed byte is actually read (views alone would time header
    parsing, not the record plane). ``row_gbps`` additionally reports
    the columnar path when per-row tuples ARE materialized
    (``iter_records``) — the host reader's shape — kept in the record
    for honesty: the gated headline is the column-scan decode, which is
    what the zero-copy format exists for. Decode is single-threaded
    pure CPU on both sides, so the A/B is fair at any core count;
    ``cores`` rides along for the ledger (the honest-caveat pattern the
    other rows follow). Gate: column-scan decode ≥ 1.5x pickle, or a
    loud ``gate_skip_reason``."""
    import io
    import os

    from sparkrdma_tpu.engine.serializer import (
        CompressionCodec,
        frame_compressed,
        iter_compressed_blocks,
        PickleSerializer,
    )
    from sparkrdma_tpu.shuffle import columnar as col
    from sparkrdma_tpu.shuffle.writer.columnar import ColumnarPartitionWriter

    rows = 40_000 if dry_run else 400_000
    n_pairs = 2 if dry_run else 5
    rng = np.random.default_rng(33)
    keys = rng.integers(0, 1 << 32, rows, dtype=np.uint32)
    vals = rng.integers(0, 1 << 31, rows, dtype=np.int64)
    records = [(k, v) for k, v in zip(keys, vals)]
    logical_bytes = keys.nbytes + vals.nbytes
    codec = CompressionCodec(enabled=True)
    ser = PickleSerializer()

    # pickle stream: the legacy sort-file framing (256 KiB flushes)
    import pickle as _pickle
    import struct as _struct

    pack = _struct.Struct(">I").pack
    pkl_stream = bytearray()
    buf = bytearray()
    for rec in records:
        data = _pickle.dumps(rec, protocol=_pickle.HIGHEST_PROTOCOL)
        buf += pack(len(data))
        buf += data
        if len(buf) >= (256 << 10):
            pkl_stream += frame_compressed(codec, bytes(buf))
            buf.clear()
    if buf:
        pkl_stream += frame_compressed(codec, bytes(buf))
    pkl_stream = bytes(pkl_stream)

    # columnar stream: the real partition writer, default batch rows
    chunks = []
    cw = ColumnarPartitionWriter(codec, chunks.append, batch_rows=4096)
    for rec in records:
        cw.write_record(rec)
    cw.flush_batch()
    assert cw.all_columnar, "bench records must conform"
    col_stream = b"".join(chunks)

    expect_keys = int(keys.sum(dtype=np.uint64) & 0xFFFFFFFFFFFFFFFF)

    def decode_pickle():
        n, ksum = 0, 0
        for block in iter_compressed_blocks(io.BytesIO(pkl_stream), codec):
            recs = list(ser.load_buffer(block))
            n += len(recs)
            ksum += int(np.add.reduce([int(r[0]) for r in recs]))
        return n, ksum & 0xFFFFFFFFFFFFFFFF

    def decode_columnar_scan():
        n, ksum, vsum = 0, 0, 0
        for block in iter_compressed_blocks(io.BytesIO(col_stream), codec):
            cols = col.decode_columns(block)
            n += len(cols[0])
            ksum += int(cols[0].sum(dtype=np.uint64))
            vsum += int(cols[1].sum(dtype=np.int64))
        return n, ksum & 0xFFFFFFFFFFFFFFFF

    def decode_columnar_rows():
        n = 0
        for block in iter_compressed_blocks(io.BytesIO(col_stream), codec):
            n += len(list(col.iter_records(block)))
        return n

    # byte identity before timing: both sides see every row
    n_p, sum_p = decode_pickle()
    n_c, sum_c = decode_columnar_scan()
    if n_p != rows or n_c != rows or sum_p != expect_keys or sum_c != expect_keys:
        raise SystemExit("BENCH FAILED: columnar A/B decode sums differ")

    pairs = []
    for _ in range(n_pairs):
        t0 = time.perf_counter()
        decode_pickle()
        t_p = time.perf_counter() - t0
        t0 = time.perf_counter()
        decode_columnar_scan()
        t_c = time.perf_counter() - t0
        t0 = time.perf_counter()
        decode_columnar_rows()
        t_r = time.perf_counter() - t0
        pairs.append({
            "pickle_gbps": round(logical_bytes / t_p / 1e9, 4),
            "columnar_gbps": round(logical_bytes / t_c / 1e9, 4),
            "columnar_row_gbps": round(logical_bytes / t_r / 1e9, 4),
        })
    med_p = float(np.median([p["pickle_gbps"] for p in pairs]))
    med_c = float(np.median([p["columnar_gbps"] for p in pairs]))
    med_r = float(np.median([p["columnar_row_gbps"] for p in pairs]))
    speedup = round(med_c / med_p, 3) if med_p else None
    gate_evaluated = not dry_run and speedup is not None
    gate_skip_reason = None
    if not gate_evaluated:
        gate_skip_reason = (
            "dry run: volume too small to resolve decode throughput"
            if dry_run else "no throughput measured"
        )
    if gate_evaluated and speedup < 1.5:
        raise SystemExit(
            f"BENCH FAILED: columnar decode {speedup}x < 1.5x over pickle "
            f"(pickle {med_p:.3f} GB/s, columnar {med_c:.3f} GB/s)"
        )
    return {
        "ab_columnar_decode": {
            "pairs": pairs,
            "rows": rows,
            "logical_mb": round(logical_bytes / 1e6, 3),
            "pickle_gbps": round(med_p, 4),
            "columnar_gbps": round(med_c, 4),
            "row_gbps": round(med_r, 4),
            "decode_speedup": speedup,
            "columnar_framed_bytes": len(col_stream),
            "pickle_framed_bytes": len(pkl_stream),
            "cores": os.cpu_count() or 1,
            "gate_evaluated": gate_evaluated,
            "gate_skip_reason": gate_skip_reason,
        }
    }


# ---------------------------------------------------------------------------
# device plane: chained-jit differencing (see module docstring)
# ---------------------------------------------------------------------------

def _chained_ms(jax, jnp, step, x, k1, k2, reps=6):
    """ms per step of ``step(state, i) -> state`` (state: device pytree).

    Differences a k2-step chain against a k1-step chain to cancel
    dispatch latency. Under rig-load spikes the difference can come
    out non-positive; fall back to the k2 chain's per-step time —
    dispatch-inclusive, so a conservative UNDER-estimate of
    throughput — rather than ever reporting a negative rate."""

    @partial(jax.jit, static_argnums=(1,))
    def runk(v, k):
        out = jax.lax.fori_loop(0, k, lambda i, v: step(v, i), v)
        leaf = jax.tree.leaves(out)[0]
        return leaf.reshape(-1)[:1].astype(jnp.float32).sum()

    def timed(k):
        float(runk(x, k))  # compile
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            float(runk(x, k))
            ts.append(time.perf_counter() - t0)
        return min(ts)

    for _ in range(2):
        t_hi = timed(k2)
        delta = t_hi - timed(k1)
        if delta > 0:
            return delta / (k2 - k1) * 1e3
    return t_hi / k2 * 1e3


def bench_device(jax) -> dict:
    import jax.numpy as jnp

    from sparkrdma_tpu.models.terasort import TeraSorter
    from sparkrdma_tpu.ops.exchange import ExchangeProgram
    from sparkrdma_tpu.ops.pallas_attention import flash_attention
    from sparkrdma_tpu.parallel.mesh import make_mesh

    out = {}
    device = jax.devices()[0]
    rng = np.random.default_rng(0)
    mesh = make_mesh([device])

    # --- TeraSort step (device_sort hot path), verified in-loop ---------
    keys = rng.integers(0, 1 << 32, size=N_KEYS, dtype=np.uint32)
    t0 = time.perf_counter()
    host_sorted = np.sort(keys)
    host_s = time.perf_counter() - t0
    sorter = TeraSorter(mesh)
    step = sorter.step(N_KEYS)
    dev_keys = jax.device_put(keys, device)
    merged, total, overflowed = step(dev_keys)
    got = np.asarray(merged)[: int(np.asarray(total)[0])]
    if bool(overflowed) or not np.array_equal(got[:N_KEYS], host_sorted):
        raise SystemExit("BENCH FAILED: device TeraSort != host sort")

    def sort_step(v, i):
        # re-disorder (xor is order-hostile; sorting stays honest)
        v = jnp.flip(v) ^ (i.astype(jnp.uint32) * jnp.uint32(2654435761))
        m, _, _ = step(v)
        return m[:N_KEYS]

    ms = _chained_ms(jax, jnp, sort_step, dev_keys, 1, 9)
    out["device_sort_gbps"] = round(N_KEYS * 4 / (ms / 1e3) / 1e9, 3)
    out["terasort_speedup_vs_host_sort"] = round(host_s / (ms / 1e3), 3)
    out["host_sort_s"] = round(host_s, 4)

    # --- flash attention vs XLA dense, same process, same method --------
    B, S, H, D = 4, 2048, 8, 128
    q = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.bfloat16)

    def attn_chain(attn_fn):
        def stepf(qkv, i):
            qq, kk, vv = qkv
            return (attn_fn(qq, kk, vv), kk, vv)  # output feeds next q

        return _chained_ms(jax, jnp, stepf, (q, k, v), 16, 272)

    flash_ms = attn_chain(
        lambda a, b, c: flash_attention(
            a, b, c, causal=True, block_q=1024, block_k=1024, interpret=False
        )
    )

    def xla_dense(a, b, c):
        qt = jnp.transpose(a, (0, 2, 1, 3)).astype(jnp.float32)
        kt = jnp.transpose(b, (0, 2, 1, 3)).astype(jnp.float32)
        vt = jnp.transpose(c, (0, 2, 1, 3))
        s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) / np.sqrt(D)
        s = jnp.where(np.tril(np.ones((S, S), bool)), s, -1e30)
        p = jax.nn.softmax(s, axis=-1).astype(jnp.bfloat16)
        o = jnp.einsum("bhqk,bhkd->bhqd", p, vt)
        return jnp.transpose(o, (0, 2, 1, 3)).astype(jnp.bfloat16)

    xla_ms = attn_chain(xla_dense)
    causal_flops = 4 * B * H * S * S * D * 0.5
    out["flash_attn_ms"] = round(flash_ms, 3)
    out["flash_attn_tflops"] = round(causal_flops / (flash_ms / 1e3) / 1e12, 2)
    out["xla_dense_attn_ms"] = round(xla_ms, 3)
    out["flash_vs_xla_dense"] = round(xla_ms / flash_ms, 2)

    # --- flash TRAINING step: forward + custom-VJP backward (the two
    # blockwise dq / dkdv Pallas kernels; 512^2 blocks measured best
    # for the VJP — 1024^2 pays VMEM pressure in the backward) --------
    def train_step(qkv, i):
        qq, kk, vv = qkv

        def lf(a, b, c):
            return flash_attention(
                a, b, c, causal=True, block_q=512, block_k=512,
                interpret=False,
            ).astype(jnp.float32).sum()

        dq, dk, dv = jax.grad(lf, argnums=(0, 1, 2))(qq, kk, vv)
        # feed gradients forward so the chain is data-dependent
        return (dq.astype(jnp.bfloat16), kk, vv)

    train_ms = _chained_ms(jax, jnp, train_step, (q, k, v), 16, 144)
    # physical floor: a fwd+bwd step cannot beat the forward alone —
    # if the differencing lands below it (dispatch jitter on a loaded
    # rig), remeasure once and then clamp to the consistent bound
    if train_ms < flash_ms:
        train_ms = _chained_ms(jax, jnp, train_step, (q, k, v), 16, 144)
    train_ms = max(train_ms, flash_ms)
    out["flash_train_ms"] = round(train_ms, 3)
    # fwd (1x) + bwd (2.5x) of the causal matmul flops
    out["flash_train_tflops"] = round(
        causal_flops * 3.5 / (train_ms / 1e3) / 1e12, 2
    )

    # --- MFU: measured TFLOPs against the chip's dense bf16 peak --------
    # peak table from public spec sheets (per device, bf16, no
    # sparsity); an unlisted kind (CPU, emulator) reports null MFU
    # rather than a made-up peak
    _BF16_PEAK_TFLOPS = {
        "tpu v4": 275.0,
        "tpu v5 lite": 197.0,
        "tpu v5e": 197.0,
        "tpu v5": 459.0,
        "tpu v5p": 459.0,
        "tpu v6 lite": 918.0,
        "tpu v6e": 918.0,
    }
    kind = str(getattr(device, "device_kind", "") or "")
    peak = _BF16_PEAK_TFLOPS.get(kind.strip().lower())
    out["device_kind"] = kind
    out["bf16_peak_tflops"] = peak
    out["flash_attn_mfu"] = (
        round(out["flash_attn_tflops"] / peak, 4) if peak else None
    )
    out["flash_train_mfu"] = (
        round(out["flash_train_tflops"] / peak, 4) if peak else None
    )

    # --- loopback exchange program executable ---------------------------
    prog = ExchangeProgram(mesh)
    block = 64 << 20
    slab = jax.device_put(
        rng.integers(0, 256, size=(1, block), dtype=np.uint8), device
    )
    counts = jax.device_put(np.asarray([block], np.int32), device)
    xfn = prog.program_for(1, block, slab.dtype)

    def ex_step(sc, i):
        s_, c_ = sc
        r, rc = xfn(s_ ^ jnp.uint8(1), c_)  # xor defeats loop collapsing
        return (r, rc)

    # long chain: per-step is sub-ms, so a short chain's difference
    # drowns in dispatch jitter (observed 27-309 GB/s run-to-run)
    ems = _chained_ms(jax, jnp, ex_step, (slab, counts), 32, 288)
    out["exchange_loopback_gbps"] = round(block / (ems / 1e3) / 1e9, 3)
    return out


def main() -> None:
    import argparse
    import os

    from sparkrdma_tpu.obs import export_chrome_trace, get_registry
    from sparkrdma_tpu.testing import faults

    parser = argparse.ArgumentParser(description="sparkrdma_tpu benchmark")
    parser.add_argument(
        "--fault-plan",
        default="",
        help="fault-injection spec, e.g. 'read:fail:2;rpc:delay:1:delay_ms=50' "
        "— exercises the resilience ladder under load (docs/RESILIENCE.md)",
    )
    parser.add_argument(
        "--fault-seed",
        type=int,
        default=0,
        help="seed for deterministic fault placement (corrupt byte choice)",
    )
    parser.add_argument(
        "--ab",
        default="",
        choices=["", "device_fetch", "concurrent_jobs", "iouring_read",
                 "consume_sharded", "profiler_overhead", "slo_overhead",
                 "journal_overhead", "columnar_decode"],
        help="run ONE A/B at reduced volume and print its JSON — the CI "
        "obs smoke's dry-run mode (e.g. --ab device_fetch)",
    )
    args = parser.parse_args()
    dry_abs = {
        "device_fetch": bench_device_fetch_ab,
        "concurrent_jobs": bench_concurrent_jobs_ab,
        "iouring_read": bench_iouring_read_ab,
        "consume_sharded": bench_consume_sharded_ab,
        "profiler_overhead": bench_profiler_overhead_ab,
        "slo_overhead": bench_slo_overhead_ab,
        "journal_overhead": bench_journal_overhead_ab,
        "columnar_decode": bench_columnar_decode_ab,
    }
    if args.ab:
        record = dry_abs[args.ab](dry_run=True)
        record["dry_run"] = True
        print(json.dumps(record))
        return
    plan = None
    if args.fault_plan:
        plan = faults.FaultPlan.parse(args.fault_plan, seed=args.fault_seed)
        faults.install(plan)

    # time-resolved telemetry: a local hub + one heartbeater make the
    # artifact a timeline instead of an end-state snapshot
    from sparkrdma_tpu.obs.telemetry import Heartbeater, TelemetryHub

    from sparkrdma_tpu.obs.profiler import acquire_profiler, release_profiler

    hub = TelemetryHub(role="bench", interval_ms=250)
    # the bench process profiles itself: its sampler rides the same
    # heartbeats, so the artifact carries a flamegraph-ready profile
    profiler = acquire_profiler(None, role="bench-proc")
    heartbeater = Heartbeater(
        get_registry(), "bench-proc", interval_ms=250, send=hub.ingest,
        profiler=profiler,
    ).start()

    out = {}
    out.update(bench_native_reads())
    out.update(bench_consume_pipelined_ab())
    out.update(bench_consume_mapped_ab())
    out.update(bench_striping_ab())
    out.update(bench_iouring_read_ab())
    out.update(bench_consume_sharded_ab())
    out.update(bench_device_fetch_ab())
    out.update(bench_concurrent_jobs_ab())
    out.update(bench_profiler_overhead_ab())
    out.update(bench_slo_overhead_ab())
    out.update(bench_journal_overhead_ab())
    out.update(bench_columnar_decode_ab())
    import jax

    out.update(bench_device(jax))
    heartbeater.stop(flush=True)
    release_profiler(profiler)
    value = out["native_read_samehost_gbps"]
    trace_path = os.environ.get("SRT_TRACE_OUT", "bench_trace.json")
    try:
        export_chrome_trace(trace_path)
    except OSError:
        trace_path = None
    record = {
        "metric": "shuffle_read_gbps_per_chip",
        "value": value,
        "unit": "GB/s",
        "vs_baseline": round(value / WIRE_RATE_GBPS, 3),
        **out,
        "n_keys": N_KEYS,
        "read_block_bytes": READ_BLOCK,
        "device": str(jax.devices()[0]),
        "note": (
            "vs_baseline = same-host one-sided READ GB/s over the "
            "12.5 GB/s 100GbE wire-rate operating point (BASELINE.md); "
            "host<->HBM staging excluded: behind the axon tunnel it "
            "would measure the tunnel, not the framework"
        ),
        "obs_registry": get_registry().snapshot(),
        "trace_file": trace_path,
        "telemetry_timeline": hub.timeline(),
        "stragglers": hub.straggler_report(),
        "profile": hub.profiles.summary(),
    }
    hub.stop()
    if plan is not None:
        record["fault_plan"] = {
            "spec": args.fault_plan,
            "seed": args.fault_seed,
            "injected": plan.total_injected,
        }
    print(json.dumps(record))


if __name__ == "__main__":
    main()
