"""Benchmark: device TeraSort shuffle step vs the host sort baseline.

The reference's only published number is HiBench TeraSort 1.41x over
stock Spark sort shuffle on 100 GbE RoCE (README.md:7-19, BASELINE.md).
This bench reproduces that comparison shape on one TPU chip: the
framework's jitted shuffle-sort step (the TeraSort partition ->
exchange -> merge pipeline, on-device) against the stock host path
(numpy sort of the same keys), reporting the speedup; ``vs_baseline``
normalizes by the reference's 1.41x.

Methodology: steady-state throughput is measured by chaining K
data-dependent steps inside ONE jitted program (re-disordering between
rounds) and differencing against a single-step run — this isolates
sustained on-chip throughput from host<->device dispatch latency, the
same way the reference's number excludes JVM startup. Output
correctness is separately verified against the host sort.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

from __future__ import annotations

import json
import time
from functools import partial

import numpy as np

REFERENCE_SPEEDUP = 1.41  # SparkRDMA TeraSort vs stock sort shuffle
N_KEYS = 1 << 25  # 32M uint32 keys = 128 MiB
CHAIN = 16


def main() -> None:
    import jax
    import jax.numpy as jnp

    from sparkrdma_tpu.models.terasort import TeraSorter
    from sparkrdma_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(0)
    keys = rng.integers(0, 1 << 32, size=N_KEYS, dtype=np.uint32)

    # -- stock path: host sort (the "Spark sort shuffle" role) ------------
    t0 = time.perf_counter()
    host_sorted = np.sort(keys)
    host_s = time.perf_counter() - t0

    # -- framework path: jitted device shuffle-sort step ------------------
    device = jax.devices()[0]
    mesh = make_mesh([device])
    sorter = TeraSorter(mesh)
    dev_keys = jax.device_put(keys, device)
    step = sorter.step(N_KEYS)

    # correctness: one full step vs the host baseline
    merged, total, overflowed = step(dev_keys)
    out = np.asarray(merged)[: int(np.asarray(total)[0])]
    if bool(overflowed) or not np.array_equal(out[:N_KEYS], host_sorted):
        raise SystemExit("BENCH FAILED: device sort != host sort")

    @partial(jax.jit, static_argnums=(1,))
    def chained(x, k):
        def body(i, v):
            # re-disorder between rounds (xor keeps the sort honest; the
            # comparison network is data-oblivious anyway)
            v = jnp.flip(v) ^ (i.astype(jnp.uint32) * jnp.uint32(2654435761))
            m, _, _ = step(v)
            return m[:N_KEYS]

        return jax.lax.fori_loop(0, k, body, x).sum()

    float(chained(dev_keys, 1))  # compile both programs
    float(chained(dev_keys, CHAIN))
    t0 = time.perf_counter()
    float(chained(dev_keys, 1))
    t1 = time.perf_counter() - t0
    t0 = time.perf_counter()
    float(chained(dev_keys, CHAIN))
    tk = time.perf_counter() - t0
    dev_s = max((tk - t1) / (CHAIN - 1), 1e-9)

    speedup = host_s / dev_s
    gbps = (N_KEYS * 4) / dev_s / 1e9
    print(
        json.dumps(
            {
                "metric": "terasort_speedup_vs_host_sort",
                "value": round(speedup, 3),
                "unit": "x",
                "vs_baseline": round(speedup / REFERENCE_SPEEDUP, 3),
                "device_sort_gbps": round(gbps, 3),
                "n_keys": N_KEYS,
                "device": str(device),
                "host_sort_s": round(host_s, 4),
                "device_step_s": round(dev_s, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
