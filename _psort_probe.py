import time, numpy as np
import jax, jax.numpy as jnp
import sparkrdma_tpu.ops.pallas_sort as ps

rng = np.random.default_rng(0)
dev = jax.devices()[0]
print("device:", dev, flush=True)

N = 1 << 25
keys = rng.integers(0, 1 << 32, size=N, dtype=np.uint32)
x32 = jax.device_put(
    (keys.astype(np.int64) - (1 << 31)).astype(np.int32), dev
)
ref32 = np.sort(np.asarray(x32))

# 1. presort alone
t0 = time.perf_counter()
f_pre = jax.jit(lambda v: ps.presort_rows(v, 8192))
r = jax.block_until_ready(f_pre(x32))
print(f"presort compile+run {time.perf_counter()-t0:.1f}s", flush=True)
t0 = time.perf_counter(); jax.block_until_ready(f_pre(x32))
t = time.perf_counter() - t0
print(f"presort {t*1e3:.1f}ms -> {N*4/t/1e9:.1f} GB/s", flush=True)

# 2. merge_block alone (one pass, k=2*block)
B = ps.MAX_BLOCK_ELEMS
t0 = time.perf_counter()
mb = jax.block_until_ready(ps.merge_block(r, B, 2 * B, False))
print(f"merge_block compile+run {time.perf_counter()-t0:.1f}s", flush=True)
t0 = time.perf_counter()
jax.block_until_ready(ps.merge_block(r, B, 2 * B, False))
t = time.perf_counter() - t0
print(f"merge_block {t*1e3:.1f}ms -> {N*4/t/1e9:.1f} GB/s", flush=True)

# 3. local_sort_blocks
t0 = time.perf_counter()
ls = jax.block_until_ready(ps.local_sort_blocks(r, 8192, B, False))
print(f"local_sort compile+run {time.perf_counter()-t0:.1f}s", flush=True)
t0 = time.perf_counter()
jax.block_until_ready(ps.local_sort_blocks(r, 8192, B, False))
t = time.perf_counter() - t0
print(f"local_sort {t*1e3:.1f}ms -> {N*4/t/1e9:.1f} GB/s", flush=True)

# 4. full sort
t0 = time.perf_counter()
f = jax.jit(lambda v: ps.sort_flat(v))
got = jax.block_until_ready(f(x32))
print(f"sort_flat compile+run {time.perf_counter()-t0:.1f}s", flush=True)
assert np.array_equal(np.asarray(got), ref32), "WRONG"
print("correct on chip", flush=True)
for _ in range(3):
    t0 = time.perf_counter(); jax.block_until_ready(f(x32))
    t = time.perf_counter() - t0
    print(f"sort_flat {t*1e3:.1f}ms -> {N*4/t/1e9:.2f} GB/s", flush=True)

# baseline
fb = jax.jit(jnp.sort)
jax.block_until_ready(fb(x32))
t0 = time.perf_counter(); jax.block_until_ready(fb(x32))
t = time.perf_counter() - t0
print(f"flat jnp.sort {t*1e3:.1f}ms -> {N*4/t/1e9:.2f} GB/s", flush=True)
